"""DistributedQueryRunner: N servers in one process with real transport.

Reference parity: testing/trino-testing/.../DistributedQueryRunner.java:94 —
one coordinator + N workers as real HTTP servers on ephemeral ports in a
single process, real discovery announcements, real page exchanges; the
standard way the reference tests its multi-node story (SURVEY §4).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog import CatalogManager
from ..client.client import StatementClient
from ..connectors.blackhole import BlackholeConnectorFactory
from ..connectors.memory import MemoryConnectorFactory
from ..connectors.tpcds import TpcdsConnectorFactory
from ..connectors.tpch import TpchConnectorFactory
from ..server.coordinator import CoordinatorServer
from ..server.worker import WorkerServer
from ..session import Session

DEFAULT_CATALOGS: Tuple[Tuple[str, str, dict], ...] = (
    ("tpch", "tpch", {"tpch.scale-factor": 0.01}),
)


def _build_catalogs(catalogs: Sequence[Tuple[str, str, dict]]) -> CatalogManager:
    cm = CatalogManager()
    cm.register_factory(TpchConnectorFactory())
    cm.register_factory(TpcdsConnectorFactory())
    cm.register_factory(MemoryConnectorFactory())
    cm.register_factory(BlackholeConnectorFactory())
    try:
        from ..connectors.hive import HiveConnectorFactory

        cm.register_factory(HiveConnectorFactory())
    except ImportError:
        pass
    for name, connector, config in catalogs:
        cm.create_catalog(name, connector, config)
    return cm


class DistributedQueryRunner:
    """Coordinator + N workers, all in-process, real HTTP between them."""

    def __init__(
        self,
        workers: int = 2,
        catalogs: Sequence[Tuple[str, str, dict]] = DEFAULT_CATALOGS,
        properties: Optional[dict] = None,
        startup_timeout: float = 10.0,
        resource_groups: Optional[dict] = None,
    ):
        self.session = Session(config=properties)
        self._catalog_spec = [
            (name, connector, dict(config))
            for name, connector, config in catalogs
        ]
        for name, connector, config in catalogs:
            self.session.create_catalog(name, connector, config)
        self.coordinator = CoordinatorServer(
            self.session, distributed=True,
            resource_groups=resource_groups,
        ).start()
        self.workers: List[WorkerServer] = []
        # real child processes (worker_main.py), killable with SIGKILL:
        # list of (Popen, node_id, uri)
        self.subprocess_workers: List[tuple] = []
        for _ in range(workers):
            w = WorkerServer(
                _build_catalogs(catalogs), self.coordinator.uri
            ).start()
            self.workers.append(w)
        self._wait_for_workers(workers, startup_timeout)
        self.client = StatementClient(self.coordinator.uri)

    def _wait_for_workers(self, n: int, timeout: float):
        deadline = time.time() + timeout
        nm = self.coordinator.coordinator.node_manager
        while time.time() < deadline:
            if len(nm.alive()) >= n:
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"only {len(nm.alive())}/{n} workers announced in {timeout}s"
        )

    # ------------------------------------------------------------------
    def execute(self, sql: str):
        """Returns (columns, rows) via the real statement protocol."""
        return self.client.execute(sql)

    def rows(self, sql: str) -> List[tuple]:
        _, rows = self.execute(sql)
        return [tuple(r) for r in rows]

    def alive_workers(self) -> int:
        return len(self.coordinator.coordinator.node_manager.alive())

    def kill_worker(self, index: int = -1) -> WorkerServer:
        w = self.workers.pop(index)
        w.stop()
        return w

    # -- real-process churn (chaos harness) ----------------------------
    def add_subprocess_worker(
        self,
        fault_injection: Optional[dict] = None,
        startup_timeout: float = 60.0,
    ) -> tuple:
        """Spawn a worker as a real child process (worker_main.py) and
        wait until it announces.  Unlike the in-process workers this one
        can be SIGKILLed for true kill -9 chaos: no drain, no goodbye,
        its sockets refuse instantly.  Returns (Popen, node_id, uri)."""
        cmd = [
            sys.executable, "-m", "trino_tpu.server.worker_main",
            "--coordinator", self.coordinator.uri,
            "--catalogs", json.dumps(
                [[n, c, cfg] for n, c, cfg in self._catalog_spec]
            ),
        ]
        if fault_injection:
            cmd += ["--fault-injection", json.dumps(fault_injection)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env,
        )
        line = proc.stdout.readline()  # blocks until the worker is up
        if not line:
            proc.kill()
            raise RuntimeError(
                "subprocess worker exited before announcing "
                f"(rc={proc.poll()})"
            )
        doc = json.loads(line)
        node_id, uri = doc["nodeId"], doc["uri"]
        nm = self.coordinator.coordinator.node_manager
        deadline = time.time() + startup_timeout
        while time.time() < deadline:
            if any(n == node_id for n, _ in nm.alive()):
                break
            time.sleep(0.05)
        else:
            proc.kill()
            raise RuntimeError(
                f"subprocess worker {node_id} never announced in "
                f"{startup_timeout}s"
            )
        entry = (proc, node_id, uri)
        self.subprocess_workers.append(entry)
        return entry

    def enable_autoscaler(self, **overrides):
        """Turn on the coordinator autoscaler with this runner's
        subprocess-worker spawner as the scale-out path: new capacity
        arrives as real child processes (late joiners, schedulable the
        moment they announce) and scale-in drains through the PR 10
        lifecycle.  Returns the Autoscaler."""
        return self.coordinator.coordinator.enable_autoscaler(
            scale_out=self.add_subprocess_worker, **overrides
        )

    def sigkill_subprocess_worker(self, index: int = -1) -> tuple:
        """kill -9 a subprocess worker: the process dies mid-whatever,
        with no chance to drain or announce.  Returns its entry."""
        entry = self.subprocess_workers.pop(index)
        proc = entry[0]
        try:
            os.kill(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return entry

    def stop(self):
        for w in self.workers:
            w.stop()
        for proc, _, _ in self.subprocess_workers:
            try:
                proc.kill()
            except Exception:
                pass
            proc.wait()
        self.subprocess_workers = []
        self.coordinator.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
