"""DistributedQueryRunner: N servers in one process with real transport.

Reference parity: testing/trino-testing/.../DistributedQueryRunner.java:94 —
one coordinator + N workers as real HTTP servers on ephemeral ports in a
single process, real discovery announcements, real page exchanges; the
standard way the reference tests its multi-node story (SURVEY §4).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog import CatalogManager
from ..client.client import StatementClient
from ..connectors.blackhole import BlackholeConnectorFactory
from ..connectors.memory import MemoryConnectorFactory
from ..connectors.tpcds import TpcdsConnectorFactory
from ..connectors.tpch import TpchConnectorFactory
from ..server.coordinator import CoordinatorServer
from ..server.worker import WorkerServer
from ..session import Session

DEFAULT_CATALOGS: Tuple[Tuple[str, str, dict], ...] = (
    ("tpch", "tpch", {"tpch.scale-factor": 0.01}),
)


def _build_catalogs(catalogs: Sequence[Tuple[str, str, dict]]) -> CatalogManager:
    cm = CatalogManager()
    cm.register_factory(TpchConnectorFactory())
    cm.register_factory(TpcdsConnectorFactory())
    cm.register_factory(MemoryConnectorFactory())
    cm.register_factory(BlackholeConnectorFactory())
    try:
        from ..connectors.hive import HiveConnectorFactory

        cm.register_factory(HiveConnectorFactory())
    except ImportError:
        pass
    for name, connector, config in catalogs:
        cm.create_catalog(name, connector, config)
    return cm


def spawn_subprocess_worker(
    coordinator_uri: str,
    catalog_spec: Sequence[Tuple[str, str, dict]],
    fault_injection: Optional[dict] = None,
    local_devices: Optional[int] = None,
    process_index: Optional[int] = None,
    host: Optional[str] = None,
) -> Tuple[subprocess.Popen, str, str]:
    """Spawn one worker as a real child process (worker_main.py) and
    block until it prints its announce line; returns (Popen, node_id,
    uri).  Shared by the in-process runner and SubprocessCoordinator —
    the caller decides how to wait for discovery adoption.

    ``local_devices``/``process_index``/``host`` turn the child into a
    host-sized capacity unit of a multi-host cluster: the process gets
    its own slice of ``local_devices`` virtual CPU devices and announces
    a topology the coordinator tracks (HOST_GONE on loss)."""
    cmd = [
        sys.executable, "-m", "trino_tpu.server.worker_main",
        "--coordinator", coordinator_uri,
        "--catalogs", json.dumps(
            [[n, c, cfg] for n, c, cfg in catalog_spec]
        ),
    ]
    if fault_injection:
        cmd += ["--fault-injection", json.dumps(fault_injection)]
    if host is not None:
        cmd += ["--host", str(host)]
    if process_index is not None:
        cmd += ["--process-index", str(process_index)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if local_devices is not None:
        # must be in the environment BEFORE the child's first jax import
        # (worker_main's enable_x64() call) — XLA reads it at backend
        # init, a CLI flag would be too late
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={int(local_devices)}"
        ).strip()
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env,
    )
    line = proc.stdout.readline()  # blocks until the worker is up
    if not line:
        proc.kill()
        raise RuntimeError(
            f"subprocess worker exited before announcing (rc={proc.poll()})"
        )
    doc = json.loads(line)
    return proc, doc["nodeId"], doc["uri"]


class DistributedQueryRunner:
    """Coordinator + N workers, all in-process, real HTTP between them."""

    def __init__(
        self,
        workers: int = 2,
        catalogs: Sequence[Tuple[str, str, dict]] = DEFAULT_CATALOGS,
        properties: Optional[dict] = None,
        startup_timeout: float = 10.0,
        resource_groups: Optional[dict] = None,
    ):
        self.session = Session(config=properties)
        self._catalog_spec = [
            (name, connector, dict(config))
            for name, connector, config in catalogs
        ]
        for name, connector, config in catalogs:
            self.session.create_catalog(name, connector, config)
        self.coordinator = CoordinatorServer(
            self.session, distributed=True,
            resource_groups=resource_groups,
        ).start()
        self.workers: List[WorkerServer] = []
        # real child processes (worker_main.py), killable with SIGKILL:
        # list of (Popen, node_id, uri)
        self.subprocess_workers: List[tuple] = []
        # monotone process-index allocator for host-sized capacity units
        self._next_process_index = 0
        for _ in range(workers):
            w = WorkerServer(
                _build_catalogs(catalogs), self.coordinator.uri
            ).start()
            self.workers.append(w)
        self._wait_for_workers(workers, startup_timeout)
        self.client = StatementClient(self.coordinator.uri)

    def _wait_for_workers(self, n: int, timeout: float):
        deadline = time.time() + timeout
        nm = self.coordinator.coordinator.node_manager
        while time.time() < deadline:
            if len(nm.alive()) >= n:
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"only {len(nm.alive())}/{n} workers announced in {timeout}s"
        )

    # ------------------------------------------------------------------
    def execute(self, sql: str):
        """Returns (columns, rows) via the real statement protocol."""
        return self.client.execute(sql)

    def rows(self, sql: str) -> List[tuple]:
        _, rows = self.execute(sql)
        return [tuple(r) for r in rows]

    def alive_workers(self) -> int:
        return len(self.coordinator.coordinator.node_manager.alive())

    def kill_worker(self, index: int = -1) -> WorkerServer:
        w = self.workers.pop(index)
        w.stop()
        return w

    # -- real-process churn (chaos harness) ----------------------------
    def add_subprocess_worker(
        self,
        fault_injection: Optional[dict] = None,
        startup_timeout: float = 60.0,
        local_devices: Optional[int] = None,
        process_index: Optional[int] = None,
        host: Optional[str] = None,
    ) -> tuple:
        """Spawn a worker as a real child process (worker_main.py) and
        wait until it announces.  Unlike the in-process workers this one
        can be SIGKILLed for true kill -9 chaos: no drain, no goodbye,
        its sockets refuse instantly.  Returns (Popen, node_id, uri).

        With ``local_devices`` (and optional ``process_index``/``host``
        identity) the child joins as a host-sized capacity unit: a
        process owning its own slice of virtual devices, announcing a
        topology the coordinator's ClusterTopology tracks."""
        if local_devices is not None and process_index is None:
            process_index = self._next_process_index
        if process_index is not None:
            self._next_process_index = max(
                self._next_process_index, process_index + 1
            )
            if host is None:
                host = f"host{process_index}"
        proc, node_id, uri = spawn_subprocess_worker(
            self.coordinator.uri, self._catalog_spec, fault_injection,
            local_devices=local_devices, process_index=process_index,
            host=host,
        )
        nm = self.coordinator.coordinator.node_manager
        deadline = time.time() + startup_timeout
        while time.time() < deadline:
            if any(n == node_id for n, _ in nm.alive()):
                break
            time.sleep(0.05)
        else:
            proc.kill()
            raise RuntimeError(
                f"subprocess worker {node_id} never announced in "
                f"{startup_timeout}s"
            )
        entry = (proc, node_id, uri)
        self.subprocess_workers.append(entry)
        return entry

    def enable_autoscaler(self, local_devices=None, **overrides):
        """Turn on the coordinator autoscaler with this runner's
        subprocess-worker spawner as the scale-out path: new capacity
        arrives as real child processes (late joiners, schedulable the
        moment they announce) and scale-in drains through the PR 10
        lifecycle.  Returns the Autoscaler.

        ``local_devices`` makes the capacity unit HOST-sized: every
        admitted worker is a process owning its own ``local_devices``
        virtual-device slice with a fresh process index — the multi-host
        elasticity path (a scale-out admits a host, a scale-in drains
        and retires one)."""
        if local_devices is None:
            scale_out = self.add_subprocess_worker
        else:
            def scale_out():
                return self.add_subprocess_worker(
                    local_devices=local_devices
                )
        return self.coordinator.coordinator.enable_autoscaler(
            scale_out=scale_out, **overrides
        )

    def sigkill_subprocess_worker(self, index: int = -1) -> tuple:
        """kill -9 a subprocess worker: the process dies mid-whatever,
        with no chance to drain or announce.  Returns its entry."""
        entry = self.subprocess_workers.pop(index)
        proc = entry[0]
        try:
            os.kill(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return entry

    def stop(self):
        for w in self.workers:
            w.stop()
        for proc, _, _ in self.subprocess_workers:
            try:
                proc.kill()
            except Exception:
                pass
            proc.wait()
        self.subprocess_workers = []
        self.coordinator.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class SubprocessCoordinator:
    """A coordinator the OS can actually kill (coordinator_main.py).

    The crash-recovery harness: the coordinator runs as a real child
    process, so SIGKILL vaporizes its in-memory query state machine
    mid-flight — only the mmap'd WAL in ``coordinator_recovery_dir``
    survives.  ``restart()`` re-spawns it on the SAME port with the same
    properties, which is exactly the production recovery contract:
    surviving subprocess workers (spawned against the fixed URI)
    re-announce within a heartbeat, the WAL replays, FTE queries resume
    from committed spools, and clients polling query-id-addressed
    nextUris reconnect through the restart grace.
    """

    def __init__(
        self,
        catalogs: Sequence[Tuple[str, str, dict]] = DEFAULT_CATALOGS,
        properties: Optional[dict] = None,
        port: int = 0,
        fault_injection: Optional[dict] = None,
        startup_timeout: float = 120.0,
    ):
        self._catalog_spec = [
            (name, connector, dict(config))
            for name, connector, config in catalogs
        ]
        self.properties = dict(properties or {})
        self.fault_injection = fault_injection
        self.startup_timeout = float(startup_timeout)
        # (Popen, node_id, uri) of workers spawned via add_worker; they
        # outlive a coordinator kill (that's the point) and re-announce
        # to the same URI once it rebinds
        self.subprocess_workers: List[tuple] = []
        self.proc: Optional[subprocess.Popen] = None
        self.uri = ""
        self.port = int(port)
        self.node_id = ""
        self._spawn(self.port, fault_injection)

    def _spawn(self, port: int, fault_injection: Optional[dict]):
        cmd = [
            sys.executable, "-m", "trino_tpu.server.coordinator_main",
            "--port", str(port),
            "--catalogs", json.dumps(
                [[n, c, cfg] for n, c, cfg in self._catalog_spec]
            ),
            "--properties", json.dumps(self.properties),
        ]
        if fault_injection:
            cmd += ["--fault-injection", json.dumps(fault_injection)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env,
        )
        line = self.proc.stdout.readline()  # blocks until it binds
        if not line:
            self.proc.kill()
            raise RuntimeError(
                "subprocess coordinator exited before announcing "
                f"(rc={self.proc.poll()})"
            )
        doc = json.loads(line)
        self.uri, self.port = doc["uri"], int(doc["port"])
        self.node_id = doc["nodeId"]

    # ------------------------------------------------------------------
    def status(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(
            f"{self.uri}/v1/status", timeout=5.0
        ) as resp:
            return json.loads(resp.read())

    def wait_for_workers(self, n: int, timeout: float = 60.0):
        """Poll /v1/status until ``n`` workers are ACTIVE (the
        coordinator is out-of-process, so its node manager is only
        reachable over HTTP)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if self.status().get("activeWorkers", 0) >= n:
                    return
            except Exception:
                pass
            time.sleep(0.1)
        raise RuntimeError(
            f"fewer than {n} workers announced to {self.uri} "
            f"in {timeout}s"
        )

    def add_worker(
        self, fault_injection: Optional[dict] = None,
        startup_timeout: float = 60.0,
    ) -> tuple:
        """Spawn a subprocess worker against this coordinator and wait
        until discovery adopts it.  Returns (Popen, node_id, uri)."""
        entry = spawn_subprocess_worker(
            self.uri, self._catalog_spec, fault_injection
        )
        self.subprocess_workers.append(entry)
        self.wait_for_workers(
            len(self.subprocess_workers), startup_timeout
        )
        return entry

    def sigkill(self) -> int:
        """kill -9 the coordinator: no drain, no flush beyond the mmap'd
        WAL pages, every client socket refuses instantly.  Workers stay
        up.  Returns the pid that died."""
        pid = self.proc.pid
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        self.proc.wait()
        return pid

    def restart(
        self, fault_injection: Optional[dict] = None,
        startup_timeout: Optional[float] = None,
    ) -> "SubprocessCoordinator":
        """Re-spawn on the SAME port with the same properties (recovery
        dir included).  A fresh fault-injection spec replaces the old
        one — the restarted coordinator usually must NOT re-arm the
        crash site that killed its predecessor."""
        if self.proc is not None and self.proc.poll() is None:
            self.sigkill()
        deadline = time.time() + (startup_timeout or self.startup_timeout)
        last_err = None
        while True:
            # the dying process's socket may linger in the kernel for a
            # beat even after SIGKILL; same-port rebind retries briefly
            try:
                self._spawn(self.port, fault_injection)
                return self
            except RuntimeError as e:
                last_err = e
                if time.time() >= deadline:
                    raise
                time.sleep(0.2)

    def stop(self):
        for proc, _, _ in self.subprocess_workers:
            try:
                proc.kill()
            except Exception:
                pass
            proc.wait()
        self.subprocess_workers = []
        if self.proc is not None:
            try:
                self.proc.kill()
            except Exception:
                pass
            self.proc.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
