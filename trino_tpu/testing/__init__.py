from .runner import DistributedQueryRunner

__all__ = ["DistributedQueryRunner"]
