"""Typed configuration + session properties.

Reference parity: airlift @Config binding (369 setters; TaskManagerConfig,
QueryManagerConfig, FeaturesConfig...) and SystemSessionProperties.java
(151 typed session properties) — reduced to the properties this engine
actually consults.  Unknown keys fail at startup, like airlift's strict
config binding; session properties are validated and typed at SET time.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    name: str
    description: str
    parse: Callable[[str], Any]
    default: Any


def _bool(s: str) -> bool:
    if str(s).lower() in ("true", "1", "yes"):
        return True
    if str(s).lower() in ("false", "0", "no"):
        return False
    raise ValueError(f"not a boolean: {s}")


def _retry_policy(s: str) -> str:
    v = str(s).strip().lower()
    if v not in ("none", "task", "query"):
        raise ValueError(f"retry_policy must be none|task|query, got: {s}")
    return v


def _padding_ladder(s: str) -> str:
    """Validate (but keep as string) the bucketed-batch ABI spec: the
    executor resolves it to an exec.shapes.PaddingLadder lazily so SET
    SESSION stays import-light."""
    from .exec.shapes import parse_ladder_spec

    parse_ladder_spec(str(s))  # raises ValueError on a bad spec
    return str(s).strip().lower()


def _megakernels(s: str) -> str:
    v = str(s).strip().lower()
    if v not in ("auto", "on", "off"):
        raise ValueError(f"megakernels must be auto|on|off, got: {s}")
    return v


def _join_distribution(s: str) -> str:
    v = str(s).strip().lower()
    if v not in ("automatic", "broadcast", "partitioned"):
        raise ValueError(
            "join_distribution_type must be "
            f"automatic|broadcast|partitioned, got: {s}"
        )
    return v


# single source of truth for the automatic join-distribution threshold
# (total build-side rows across all tasks/devices)
BROADCAST_JOIN_THRESHOLD_ROWS = 1 << 20

SESSION_PROPERTIES: Dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        PropertyMetadata(
            "group_capacity",
            "initial group-by hash capacity (recompile-on-overflow)",
            int, 4096,
        ),
        PropertyMetadata(
            "query_max_memory_bytes",
            "per-query device memory reservation limit",
            int, 8 << 30,
        ),
        PropertyMetadata(
            "query_max_total_memory_bytes",
            "per-query reservation limit summed across every node and "
            "pool (query.max-total-memory analog; 0 = unlimited)",
            int, 0,
        ),
        PropertyMetadata(
            "low_memory_killer_policy",
            "victim selection when a node is blocked on memory: none | "
            "total-reservation | total-reservation-on-blocked-nodes",
            str, "total-reservation-on-blocked-nodes",
        ),
        PropertyMetadata(
            "memory_admission_timeout_s",
            "seconds a query may wait in the memory admission queue "
            "before failing with an exceeded-memory error",
            float, 60.0,
        ),
        PropertyMetadata(
            "memory_blocked_timeout_s",
            "seconds a blocked memory reservation waits for frees, "
            "revocation, or a killer verdict before raising",
            float, 0.0,
        ),
        PropertyMetadata(
            "resource_group_queue_deadline_s",
            "default per-group queue deadline: queries queued longer "
            "are shed with a retryable ADMISSION_TIMEOUT instead of "
            "waiting forever (0 = queue forever); groups may override "
            "via queueDeadlineS",
            float, 0.0,
        ),
        PropertyMetadata(
            "autoscale_min_workers",
            "autoscaler floor: scale-in never drains below this many "
            "ACTIVE workers",
            int, 1,
        ),
        PropertyMetadata(
            "autoscale_max_workers",
            "autoscaler ceiling: scale-out stops adding workers here",
            int, 4,
        ),
        PropertyMetadata(
            "autoscale_backlog_high",
            "queued queries (groups + memory admission) that count as "
            "sustained overload and trigger scale-out",
            int, 4,
        ),
        PropertyMetadata(
            "autoscale_cooldown_s",
            "seconds between autoscaler actions (anti-flap)",
            float, 2.0,
        ),
        PropertyMetadata(
            "autoscale_idle_grace_s",
            "seconds of empty backlog before scale-in drains a worker",
            float, 1.5,
        ),
        PropertyMetadata(
            "distributed",
            "execute over the full device mesh instead of one device",
            _bool, False,
        ),
        PropertyMetadata(
            "num_devices",
            "mesh size for distributed execution (0 = all devices)",
            int, 0,
        ),
        PropertyMetadata(
            "cross_host_mesh",
            "multi-host clusters: run eligible fragments as per-host "
            "shard_map slices of the global mesh, with repartition and "
            "partial-aggregate merges crossing the network exchange",
            _bool, False,
        ),
        PropertyMetadata(
            "join_distribution_type",
            "automatic | broadcast | partitioned "
            "(DetermineJoinDistributionType analog)",
            _join_distribution, "automatic",
        ),
        PropertyMetadata(
            "broadcast_join_threshold_rows",
            "automatic mode: build sides with more estimated rows are "
            "hash-partitioned instead of replicated (join-max-broadcast-"
            "table-size analog, in rows)",
            int, BROADCAST_JOIN_THRESHOLD_ROWS,
        ),
        PropertyMetadata(
            "spill_enabled",
            "allow out-of-core execution when input exceeds the memory limit",
            _bool, True,
        ),
        PropertyMetadata(
            "jit_fragments",
            "compile each fragment into one cached XLA program "
            "(off: eager op-by-op, used by EXPLAIN ANALYZE)",
            _bool, True,
        ),
        PropertyMetadata(
            "dynamic_filtering",
            "prune probe-side scans with build-side join domains",
            _bool, True,
        ),
        PropertyMetadata(
            "retry_policy",
            "failure recovery: none (pipelined) | task (FTE over spool) "
            "| query (whole-query re-dispatch on retriable failure)",
            _retry_policy, "none",
        ),
        PropertyMetadata(
            "query_retry_attempts",
            "retry_policy=query: whole-query re-dispatches before the "
            "failure is surfaced (query-retry-attempts analog)",
            int, 2,
        ),
        PropertyMetadata(
            "node_gone_grace_s",
            "continuous heartbeat silence before a SUSPECT/DRAINING node "
            "is declared GONE and its tasks reassigned "
            "(failure-detector GC-pause tolerance, seconds)",
            float, 10.0,
        ),
        PropertyMetadata(
            "exchange_retry_attempts",
            "transient exchange-fetch tries per failure streak before "
            "the upstream worker is declared dead",
            int, 3,
        ),
        PropertyMetadata(
            "exchange_retry_budget_s",
            "wall-clock budget for one exchange-fetch failure streak "
            "(exchange.max-error-duration analog, seconds)",
            float, 5.0,
        ),
        PropertyMetadata(
            "fault_injection",
            "seeded fault-injection spec (JSON: {seed, site: rule...}) "
            "threaded to workers for chaos testing; empty = off",
            str, "",
        ),
        PropertyMetadata(
            "device_fault_max_strikes",
            "device faults inside the strike window before the device is "
            "blacklisted for the process lifetime",
            int, 3,
        ),
        PropertyMetadata(
            "device_probe_backoff_s",
            "base backoff between canary re-probes of a quarantined "
            "device (doubles per failure, capped)",
            float, 1.0,
        ),
        PropertyMetadata(
            "device_watchdog_timeout_s",
            "watchdog timeout on the supervised kernel-dispatch thread; "
            "a dispatch exceeding it is treated as a device wedge (0=off)",
            float, 60.0,
        ),
        PropertyMetadata(
            "device_cpu_fallback",
            "degraded mode: re-run fragments on the CPU backend after a "
            "device fault instead of failing the task",
            _bool, True,
        ),
        PropertyMetadata(
            "flight_recorder_dir",
            "directory for the crash-safe on-disk dispatch ring (mmap'd "
            "JSONL segments, scripts/flightrec.py reads them); empty "
            "keeps the flight recorder in-memory only",
            str, "",
        ),
        PropertyMetadata(
            "flight_recorder_max_records",
            "bound on the flight-recorder dispatch ring (oldest records "
            "rotate out)",
            int, 512,
        ),
        PropertyMetadata(
            "event_journal_dir",
            "directory for the crash-safe engine-wide incident journal "
            "(mmap'd JSONL segments, scripts/doctor.py reads them); "
            "empty keeps the journal in-memory only",
            str, "",
        ),
        PropertyMetadata(
            "event_journal_max_bytes",
            "byte budget of the on-disk incident journal (the two "
            "segments rotate, oldest events drop first)",
            int, 1 << 20,
        ),
        PropertyMetadata(
            "coordinator_recovery_dir",
            "directory for the coordinator's write-ahead intent log "
            "(mmap'd torn-tail-tolerant JSONL segments journaling every "
            "query-state transition); on boot the coordinator replays "
            "it, resuming FTE queries from committed spools and failing "
            "pipelined ones with a retryable COORDINATOR_RESTART error; "
            "empty disables crash recovery",
            str, "",
        ),
        PropertyMetadata(
            "coordinator_recovery_window_s",
            "how long a restarted coordinator answers polls for "
            "still-recovering queries with 503+Retry-After (instead of "
            "404) and waits for discovery re-announcements to rebuild "
            "the live worker set before dispatching resumed work",
            float, 10.0,
        ),
        PropertyMetadata(
            "compile_observatory_dir",
            "directory for the crash-safe engine-wide compile ledger "
            "(mmap'd JSONL segments plus per-writer census snapshots, "
            "scripts/bucket_ladder.py reads them); empty keeps the "
            "observatory in-memory only",
            str, "",
        ),
        PropertyMetadata(
            "compile_census_max_families",
            "bound on distinct kernel families the shape census tracks "
            "(overflow folds into __other__, never dropped)",
            int, 64,
        ),
        PropertyMetadata(
            "serving_observatory_dir",
            "directory for the crash-safe per-signature workload census "
            "(mmap'd torn-tail-tolerant JSONL segments, merged across "
            "restarts and backfilled from the persisted query history); "
            "empty keeps the serving observatory in-memory only",
            str, "",
        ),
        PropertyMetadata(
            "serving_observatory_max_bytes",
            "byte budget for the serving observatory's two on-disk "
            "census segments",
            int, 1 << 20,
        ),
        PropertyMetadata(
            "signature_census_max",
            "bound on distinct plan signatures the workload census "
            "profiles (overflow folds into __other__, never dropped)",
            int, 128,
        ),
        PropertyMetadata(
            "slo_latency_target_s",
            "default per-tenant latency objective: a finished query "
            "slower than this (or any failed query) burns its tenant's "
            "SLO error budget",
            float, 1.0,
        ),
        PropertyMetadata(
            "slo_error_budget",
            "default fraction of a tenant's queries allowed to violate "
            "the latency objective before the burn rate exceeds 1.0",
            float, 0.1,
        ),
        PropertyMetadata(
            "slo_fast_window_s",
            "fast SLO burn-rate window (page-now signal; a burn past "
            "slo_burn_threshold here journals a throttled slo_burn "
            "event)",
            float, 30.0,
        ),
        PropertyMetadata(
            "slo_slow_window_s",
            "slow SLO burn-rate window (sustained-breach signal for "
            "system.runtime.slos and the webui panel)",
            float, 300.0,
        ),
        PropertyMetadata(
            "slo_burn_threshold",
            "fast-window burn rate above which the serving observatory "
            "journals slo_burn and the query doctor starts citing it",
            float, 2.0,
        ),
        PropertyMetadata(
            "query_doctor",
            "run the automated query doctor at query finalize and "
            "attach its ranked root-cause diagnosis to EXPLAIN ANALYZE, "
            "system.runtime.diagnoses, and the query history",
            _bool, True,
        ),
        PropertyMetadata(
            "bandwidth_ledger",
            "bracket every supervised dispatch with block_until_ready "
            "and account bytes-touched / device wall into per-kernel "
            "effective GB/s (EXPLAIN ANALYZE always collects it)",
            _bool, False,
        ),
        PropertyMetadata(
            "reorder_joins",
            "stats-based join-graph reordering (ReorderJoins / "
            "EliminateCrossJoins analogs); off keeps the FROM order",
            _bool, True,
        ),
        PropertyMetadata(
            "distinct_agg_rewrite",
            "decompose global count(DISTINCT x) into count over a "
            "hash-partitionable Distinct (scales out / tiles)",
            _bool, True,
        ),
        PropertyMetadata(
            "direct_address_joins",
            "probe stats-proven-unique dense integer build keys through "
            "a direct-address table (one gather) instead of sort-merge",
            _bool, True,
        ),
        PropertyMetadata(
            "compaction",
            "tighten survivors of selective filters/joins into a smaller "
            "static capacity (downstream ops run at the reduced width)",
            _bool, True,
        ),
        PropertyMetadata(
            "fd_group_key_pruning",
            "drop group-by keys functionally dependent (via unique-build "
            "joins) on another key; they return as arbitrary() values",
            _bool, True,
        ),
        PropertyMetadata(
            "memo_optimizer",
            "iterative Memo exploration with cost-compared alternatives "
            "(join order/commutation/distribution); off keeps the greedy "
            "single-pass choices",
            _bool, True,
        ),
        PropertyMetadata(
            "statistics_enabled",
            "cost the plan from collected/connector table statistics "
            "(histograms, NDV); off degrades every table to a bare "
            "row count (statistics-enabled analog)",
            _bool, True,
        ),
        PropertyMetadata(
            "analyze_histogram_buckets",
            "equi-height histogram buckets ANALYZE collects per "
            "numeric/date column (device-sort quantile boundaries)",
            int, 8,
        ),
        PropertyMetadata(
            "adaptive_replan_factor",
            "FTE: replan the undispatched remainder when a fragment's "
            "observed output rows diverge from the estimate by this "
            "multiple in either direction (0 disables)",
            float, 4.0,
        ),
        PropertyMetadata(
            "in_list_pushdown",
            "derive discrete-value TupleDomains from IN lists for "
            "connector split/row-group pruning",
            _bool, True,
        ),
        PropertyMetadata(
            "column_pruning",
            "prune unreferenced columns into table scans "
            "(PruneUnreferencedOutputs)",
            _bool, True,
        ),
        PropertyMetadata(
            "topn_initial_factor",
            "initial TopN candidate-set multiple (the two-phase top_k "
            "path's 4n base grows by this)",
            int, 1,
        ),
        PropertyMetadata(
            "scan_cache_enabled",
            "cache device-resident scans across queries (warm-HBM reuse)",
            _bool, True,
        ),
        PropertyMetadata(
            "result_cache",
            "serve repeated deterministic queries from the fragment "
            "result cache (invalidated by connector data versions)",
            _bool, True,
        ),
        PropertyMetadata(
            "result_cache_max_bytes",
            "in-memory byte budget for the fragment result cache "
            "(cold entries spill to disk as checksummed frames)",
            int, 256 << 20,
        ),
        PropertyMetadata(
            "compile_cache",
            "share compiled XLA fragment executables across queries and "
            "sessions (off: per-executor jit only)",
            _bool, True,
        ),
        PropertyMetadata(
            "compile_cache_dir",
            "persistent compile-cache directory shared across processes "
            "(jax persistent compilation cache + fragment index); "
            "empty = in-memory only",
            str, "",
        ),
        PropertyMetadata(
            "padding_ladder",
            "bucketed-batch ABI rungs every padded capacity quantizes "
            "onto before tracing: geometric (128*2^k, the default) | "
            "off (legacy next-multiple-of-128) | explicit "
            "comma-separated rung list",
            _padding_ladder, "geometric",
        ),
        PropertyMetadata(
            "padding_ladder_file",
            "census-tuned ladder JSON written by scripts/bucket_ladder.py "
            "--emit; when set (and readable) it overrides padding_ladder; "
            "empty = use the padding_ladder spec",
            str, "",
        ),
        PropertyMetadata(
            "compile_prewarm",
            "at session/worker boot with compile_cache_dir set, pre-warm "
            "the persistent tier's indexed rung shapes (page-cache reads "
            "+ observatory family seeding) so cold restarts reach "
            "zero-retrace steady state without shape-miss classification",
            _bool, True,
        ),
        PropertyMetadata(
            "device_generation",
            "materialize counter-based generator scans (tpch) directly "
            "in HBM instead of host numpy + upload",
            _bool, True,
        ),
        PropertyMetadata(
            "megakernels",
            "fused scan->filter->aggregate pallas megakernels (one VMEM "
            "pass per scan column): auto (TPU only) | on (forces "
            "interpret mode off-TPU, for parity tests) | off",
            _megakernels, "auto",
        ),
        PropertyMetadata(
            "double_buffer_depth",
            "streaming tiles staged (host-decoded + H2D-uploaded) ahead "
            "of the executing tile; each staged tile holds its scan "
            "working set in HBM",
            int, 1,
        ),
        PropertyMetadata(
            "donate_pages",
            "donate per-dispatch scan-page buffers to the fused program "
            "(jit donate_argnums) so XLA reuses their HBM in place; "
            "cache-resident pages are never donated",
            _bool, True,
        ),
        PropertyMetadata(
            "client_page_rows",
            "rows per protocol result page (client paging chunk)",
            int, 10000,
        ),
        PropertyMetadata(
            "fte_max_attempts",
            "FTE: attempts per task before the query fails",
            int, 4,
        ),
        PropertyMetadata(
            "fte_task_timeout_s",
            "FTE: per-attempt wall-clock timeout (seconds)",
            float, 300.0,
        ),
        PropertyMetadata(
            "fte_speculation_factor",
            "FTE: speculate when a task exceeds this multiple of the "
            "median completed sibling duration",
            float, 2.0,
        ),
        PropertyMetadata(
            "fte_speculation_min_s",
            "FTE: minimum straggler age before speculation (seconds)",
            float, 0.75,
        ),
        PropertyMetadata(
            "speculative_execution",
            "FTE: launch backup attempts for straggler tasks "
            "(EventDrivenFaultTolerantQueryScheduler SPECULATIVE class)",
            _bool, True,
        ),
        PropertyMetadata(
            "operator_stats",
            "collect per-operator OperatorStats frames (rows/bytes/wall/"
            "blocked) on every execution; forces eager per-node timing",
            _bool, False,
        ),
        PropertyMetadata(
            "query_history_dir",
            "directory for the crash-safe persisted query history store "
            "(mmap'd JSONL segments); empty = process-memory only",
            str, "",
        ),
        PropertyMetadata(
            "query_history_max_bytes",
            "byte budget of the persisted query history store (oldest "
            "completed queries evicted first)",
            int, 1 << 20,
        ),
        PropertyMetadata(
            "straggler_dispersion_factor",
            "flag/hedge a task when its wall sits this many robust "
            "deviations (MAD units) above the sibling median",
            float, 2.0,
        ),
    ]
}


class SessionProperties:
    """Per-session typed property bag (Session.java + SET SESSION)."""

    def __init__(self, overrides: Dict[str, Any] | None = None):
        self._values: Dict[str, Any] = {}
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def set(self, name: str, value):
        meta = SESSION_PROPERTIES.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        self._values[name] = (
            meta.parse(value) if isinstance(value, str) else value
        )

    def get(self, name: str):
        meta = SESSION_PROPERTIES.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        return self._values.get(name, meta.default)

    def show(self) -> list:
        return [
            (name, str(self.get(name)), str(meta.default), meta.description)
            for name, meta in sorted(SESSION_PROPERTIES.items())
        ]
