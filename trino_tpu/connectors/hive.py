"""Hive-style connector: parquet tables in a warehouse directory.

Reference parity: plugin/trino-hive (HiveMetadata, HiveSplitManager +
BackgroundHiveSplitLoader, HivePageSourceProvider) over lib/trino-parquet
(ParquetReader.java:85 — row-group/column-chunk iteration, nextPage:239;
predicate/ min-max row-group pruning -> FilteredRowRanges).

TPU-first redesign: the reference hand-decodes parquet encodings into
Blocks; here Arrow (pyarrow) is the C-backed column-chunk decoder (the
"Arrow-based column chunks -> direct HBM upload" plan of SURVEY §7 step 8)
and this module does the engine-side work the reference does around its
decoder: table discovery, schema mapping into engine types, a split per
(file, row-group) so scans parallelize across workers, min/max row-group
pruning from footer statistics against the pushed-down constraint, string
dictionary-encoding for device-friendly int32 codes, and decimal/date/
timestamp normalization into the engine's device representations.

Catalog config: {"hive.warehouse-dir": path}. Layout:
  {warehouse}/{table}/*.parquet       (all files share one schema)

Storage goes through the trino_tpu.fs object-store layer (listing,
fingerprinting, sidecar IO, part-file writes and overwrite deletes), so
hive tables inherit its atomic-PUT semantics and seeded objstore_*
fault sites; parquet FOOTER/row-group reads use the store's
``local_path()`` escape hatch because pyarrow wants real file paths.
"""
from __future__ import annotations

import hashlib
import io
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..fs import LocalObjectStore, ObjectStoreError
from ..page import Column, Page
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSink,
    PageSinkProvider,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
except ImportError:  # pragma: no cover
    pa = None
    pq = None


def _read_arrow_table(path: str, fmt: str):
    """Read a whole non-parquet file as an arrow table (the
    lib/trino-orc / trino-hive-formats reader slot, via arrow)."""
    if fmt == "orc":
        from pyarrow import orc as _orc

        return _orc.ORCFile(path).read()
    if fmt == "csv":
        from pyarrow import csv as _csv

        return _csv.read_csv(path)
    if fmt == "json":
        from pyarrow import json as _json

        return _json.read_json(path)
    raise NotImplementedError(f"unsupported hive format {fmt}")


def _require_pyarrow():
    if pq is None:  # pragma: no cover
        raise RuntimeError("hive connector requires pyarrow")


def _arrow_to_engine_type(at) -> T.Type:
    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_int8(at):
        return T.TINYINT
    if pa.types.is_int16(at):
        return T.SMALLINT
    if pa.types.is_int32(at):
        return T.INTEGER
    if pa.types.is_int64(at):
        return T.BIGINT
    if pa.types.is_float32(at):
        return T.REAL
    if pa.types.is_float64(at):
        return T.DOUBLE
    if pa.types.is_date32(at) or pa.types.is_date64(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_decimal(at):
        # <= 18 digits: one int64 limb; 19..38: two-limb wide lanes
        return T.decimal(at.precision, at.scale)
    if (
        pa.types.is_string(at)
        or pa.types.is_large_string(at)
        or pa.types.is_dictionary(at)
    ):
        return T.VARCHAR
    raise NotImplementedError(f"unsupported parquet type {at}")


def _engine_to_arrow_type(t: T.Type):
    if t.is_dictionary:
        return pa.string()
    if t.is_decimal:
        return pa.decimal128(t.precision, t.scale)
    arrow = {
        "boolean": pa.bool_(), "tinyint": pa.int8(),
        "smallint": pa.int16(), "integer": pa.int32(),
        "bigint": pa.int64(), "double": pa.float64(),
        "real": pa.float32(), "date": pa.date32(),
        "timestamp": pa.timestamp("us"),
    }.get(t.name)
    if arrow is None:
        raise NotImplementedError(
            f"hive CREATE TABLE: unsupported column type {t}"
        )
    return arrow


class HiveMetadata(ConnectorMetadata):
    def __init__(self, warehouse: str,
                 connector: Optional["HiveConnector"] = None,
                 fs: Optional[LocalObjectStore] = None):
        self.warehouse = warehouse
        self.connector = connector
        self.fs = fs if fs is not None else LocalObjectStore(warehouse)

    FORMATS = ("parquet", "orc", "csv", "json")  # hive-formats analog
    # ANALYZE sidecar (metastore table-parameters analog).  Dot-prefixed
    # on purpose: table discovery globs `*.{ext}`, which skips dotfiles,
    # so the sidecar can share the table directory without being
    # discovered as data — and data_version() skips dotfiles so writing
    # it doesn't invalidate the very version it is keyed by.
    STATS_SIDECAR = ".trino_stats.json"

    def list_tables(self) -> List[str]:
        tables = set()
        for e in self.fs.list_files():
            parts = e.path.split("/")
            if (
                len(parts) == 2
                and parts[1].rsplit(".", 1)[-1].lower() in self.FORMATS
            ):
                tables.add(parts[0])
        return sorted(tables)

    def _files(self, table: str) -> List[str]:
        """Data-file paths of one table, as REAL paths (pyarrow readers
        need them) — but discovered via the object store so listing
        passes the fault sites like any other storage op."""
        entries = self.fs.list_files(table)
        for ext in self.FORMATS:
            files = sorted(
                self.fs.local_path(e.path)
                for e in entries
                if e.path.rsplit(".", 1)[-1].lower() == ext
            )
            if files:
                return files
        raise KeyError(f"hive table not found: {table}")

    @staticmethod
    def _format_of(path: str) -> str:
        return path.rsplit(".", 1)[-1].lower()

    def get_table_schema(self, table: str) -> TableSchema:
        _require_pyarrow()
        path = self._files(table)[0]
        fmt = self._format_of(path)
        if fmt == "parquet":
            schema = pq.read_schema(path)
        else:
            schema = _read_arrow_table(path, fmt).schema
        return TableSchema(
            table,
            tuple(
                ColumnSchema(f.name, _arrow_to_engine_type(f.type))
                for f in schema
            ),
        )

    def create_table(self, schema: TableSchema) -> None:
        """CREATE TABLE [AS]: materialize the schema as an empty parquet
        file so discovery (footer-based) sees the table immediately; the
        scaled writer sink then adds part files beside it."""
        _require_pyarrow()
        fields = [
            pa.field(c.name, _engine_to_arrow_type(c.type))
            for c in schema.columns
        ]
        empty = pa.table(
            {f.name: pa.array([], f.type) for f in fields},
            schema=pa.schema(fields),
        )
        buf = io.BytesIO()
        pq.write_table(empty, buf)
        self.fs.write_file(
            f"{schema.name}/schema-0.parquet", buf.getvalue()
        )

    def _sidecar_key(self, table: str) -> str:
        return f"{table}/{self.STATS_SIDECAR}"

    def store_table_statistics(
        self, table: str, stats: TableStatistics, data_version: int
    ) -> None:
        import json

        self._files(table)  # raises KeyError for unknown tables
        doc = {
            "data_version": int(data_version),
            "row_count": stats.row_count,
            "columns": {
                name: {
                    "distinct_count": c.distinct_count,
                    "null_fraction": c.null_fraction,
                    "min_value": c.min_value,
                    "max_value": c.max_value,
                    "histogram": (
                        None if c.histogram is None
                        else [list(b) for b in c.histogram]
                    ),
                }
                for name, c in stats.columns.items()
            },
        }
        # atomic PUT via the object store (no torn sidecars); the store
        # never lists dotfiles, so this does not move data_version
        self.fs.write_file(
            self._sidecar_key(table), json.dumps(doc).encode()
        )

    def _sidecar_statistics(self, table: str) -> Optional[TableStatistics]:
        """Persisted ANALYZE results, iff still keyed to the current
        data_version (files changed since collection -> stale)."""
        import json

        if self.connector is None:
            return None
        try:
            doc = json.loads(self.fs.read_file(self._sidecar_key(table)))
        except (ObjectStoreError, ValueError):
            return None
        if int(doc.get("data_version", -1)) != self.connector.data_version(table):
            return None
        return TableStatistics(
            row_count=float(doc["row_count"]),
            columns={
                name: ColumnStatistics(
                    distinct_count=c.get("distinct_count"),
                    null_fraction=float(c.get("null_fraction") or 0.0),
                    min_value=c.get("min_value"),
                    max_value=c.get("max_value"),
                    histogram=(
                        None if c.get("histogram") is None
                        else tuple(tuple(b) for b in c["histogram"])
                    ),
                )
                for name, c in doc.get("columns", {}).items()
            },
        )

    def get_table_statistics(self, table: str) -> TableStatistics:
        """ANALYZE sidecar when fresh; else row counts from footers and
        per-column min/max/nulls from row-group statistics (the reference
        reads these via ParquetMetadata for CBO).  Non-parquet formats
        report row counts only."""
        _require_pyarrow()
        side = self._sidecar_statistics(table)
        if side is not None:
            return side
        files = self._files(table)
        if self._format_of(files[0]) != "parquet":
            rows = sum(
                _read_arrow_table(p, self._format_of(p)).num_rows
                for p in files
            )
            return TableStatistics(float(rows), {})
        rows = 0
        mins: Dict[str, float] = {}
        maxs: Dict[str, float] = {}
        nulls: Dict[str, int] = {}
        for path in self._files(table):
            md = pq.ParquetFile(path).metadata
            rows += md.num_rows
            for rg in range(md.num_row_groups):
                g = md.row_group(rg)
                for ci in range(g.num_columns):
                    col = g.column(ci)
                    st = col.statistics
                    if st is None or not st.has_min_max:
                        continue
                    name = col.path_in_schema
                    try:
                        lo, hi = float(st.min), float(st.max)
                    except (TypeError, ValueError):
                        continue
                    mins[name] = min(mins.get(name, lo), lo)
                    maxs[name] = max(maxs.get(name, hi), hi)
                    if st.null_count is not None:
                        nulls[name] = nulls.get(name, 0) + st.null_count
        cols = {
            name: ColumnStatistics(
                min_value=mins[name],
                max_value=maxs[name],
                null_fraction=nulls.get(name, 0) / max(rows, 1),
            )
            for name in mins
        }
        return TableStatistics(float(rows), cols)


class HiveSplitManager(SplitManager):
    """One split per (file, row-group); row groups whose footer min/max
    cannot satisfy the pushed-down constraint are pruned here — the
    engine-side analog of lib/trino-parquet predicate/ row-group pruning."""

    def __init__(self, metadata: HiveMetadata, connector=None):
        self.meta = metadata
        self.connector = connector

    def _pruning_enabled(self) -> bool:
        if self.connector is None:
            return True
        return bool(
            self.connector.get_session_property("row_group_pruning")
        )

    def get_splits(self, table, desired, constraint=None) -> List[Split]:
        _require_pyarrow()
        if not self._pruning_enabled():
            constraint = None
        files = self.meta._files(table)
        if HiveMetadata._format_of(files[0]) != "parquet":
            # ORC/CSV/JSON: one split per file (no engine-side footer
            # pruning; ORC stripe stats live with the reader)
            return [
                Split(table, i, len(files), {"path": p, "row_group": -1})
                for i, p in enumerate(files)
            ]
        ranges = {}
        for entry in constraint or ():
            c, lo, hi = entry[0], entry[1], entry[2]
            values = entry[3] if len(entry) > 3 else None
            ranges[c] = (lo, hi, values)
        work: List[Tuple[str, int]] = []
        for path in files:
            md = pq.ParquetFile(path).metadata
            for rg in range(md.num_row_groups):
                if ranges and self._pruned(md.row_group(rg), ranges):
                    continue
                work.append((path, rg))
        return [
            Split(table, i, len(work), {"path": path, "row_group": rg})
            for i, (path, rg) in enumerate(work)
        ]

    @staticmethod
    def _pruned(group, ranges: Dict[str, Tuple]) -> bool:
        for ci in range(group.num_columns):
            col = group.column(ci)
            r = ranges.get(col.path_in_schema)
            if r is None:
                continue
            st = col.statistics
            if st is None or not st.has_min_max:
                continue
            lo, hi, values = r
            try:
                smin, smax = float(st.min), float(st.max)
            except (TypeError, ValueError):
                continue  # non-numeric stats: cannot prune safely
            if values is not None and not any(
                smin <= v <= smax for v in values
            ):
                # discrete ValueSet: no admissible value intersects the
                # row group's [min, max] (IN-list pruning beats the plain
                # range when values are sparse)
                return True
            if (lo is not None and smax < lo) or (
                hi is not None and smin > hi
            ):
                return True
        return False


class HivePageSource(PageSource):
    def __init__(self, split: Split, columns: Sequence[str]):
        self.split = split
        self.columns = list(columns)
        self._dicts: Dict[str, np.ndarray] = {}

    def pages(self):
        _require_pyarrow()
        path = self.split.info["path"]
        rg = int(self.split.info["row_group"])
        if rg < 0:  # whole-file split: ORC/CSV/JSON formats
            fmt = HiveMetadata._format_of(path)
            tbl = _read_arrow_table(path, fmt).select(self.columns)
        else:
            pf = pq.ParquetFile(path)
            tbl = pf.read_row_group(rg, columns=self.columns)
        n = tbl.num_rows
        cols = []
        for name in self.columns:
            arr = tbl.column(name).combine_chunks()
            cols.append(self._to_column(name, arr, n))
        yield Page(cols, n, self.columns)

    def _to_column(self, name: str, arr, n: int) -> Column:
        at = arr.type
        validity = None
        if arr.null_count:
            validity = np.asarray(arr.is_valid())
        t = _arrow_to_engine_type(at)
        if t.is_dictionary:
            enc = (
                arr
                if pa.types.is_dictionary(at)
                else arr.dictionary_encode()
            )
            d = np.array(
                [str(s) for s in enc.dictionary.to_pylist()], dtype=object
            )
            codes = np.asarray(
                enc.indices.fill_null(-1), dtype=np.int32
            )
            self._dicts[name] = d
            return Column(t, codes, validity, d)
        if t.name == "date":
            days = arr.cast(pa.int32()) if pa.types.is_date32(at) else (
                arr.cast(pa.timestamp("ms")).cast(pa.int64())
            )
            vals = np.asarray(days.fill_null(0), dtype=np.int32)
            if not pa.types.is_date32(at):
                vals = (vals // 86_400_000).astype(np.int32)
            return Column(t, vals, validity)
        if t.name == "timestamp":
            us = arr.cast(pa.timestamp("us")).cast(pa.int64())
            return Column(
                t, np.asarray(us.fill_null(0), dtype=np.int64), validity
            )
        if t.is_decimal:
            # arrow decimal128 stores little-endian 16-byte integers:
            # the low limb IS the two's-complement scaled value for
            # <= 18 digits (single-limb read, zero-copy); wide decimals
            # (19..38) read BOTH limbs into the engine's (n, 2) lane
            # (Int128ArrayBlock.java:28 layout)
            ints = arr.cast(pa.decimal128(at.precision, at.scale))
            if hasattr(ints, "combine_chunks"):
                ints = ints.combine_chunks()
            wide = getattr(t, "wide", False)
            buf = ints.buffers()[1]
            if buf is None:
                vals = np.zeros((n, 2) if wide else n, dtype=np.int64)
            else:
                data = np.frombuffer(buf, dtype=np.int64)
                lo = ints.offset * 2
                lo_limbs = np.ascontiguousarray(
                    data[lo : lo + 2 * len(ints) : 2]
                )
                if wide:
                    hi_limbs = np.ascontiguousarray(
                        data[lo + 1 : lo + 2 * len(ints) : 2]
                    )
                    vals = np.stack([lo_limbs, hi_limbs], axis=-1)
                else:
                    vals = lo_limbs
                if validity is not None:
                    # arrow leaves null-slot bytes undefined; keep the
                    # engine's null-slots-are-zero convention
                    mask = validity[:, None] if wide else validity
                    vals = np.where(mask, vals, 0)
            return Column(t, vals, validity)
        vals = np.asarray(arr.fill_null(0), dtype=t.np_dtype)
        return Column(t, vals, validity)

    def dictionaries(self) -> Dict[str, np.ndarray]:
        return dict(self._dicts)


class HivePageSourceProvider(PageSourceProvider):
    def create_page_source(self, split: Split, columns) -> HivePageSource:
        return HivePageSource(split, columns)


class HiveConnector(Connector):
    # Backing files may change on disk, so the cache key embeds a
    # filesystem fingerprint: data_version() hashes every table file's
    # (path, mtime_ns, size).  The reference leans on LazyBlock + the OS
    # page cache for warm re-reads (lib/trino-parquet ParquetReader.java
    # :239); here the warm tier is device HBM via DeviceScanCache, and a
    # touched/changed/added file changes the version -> cache miss.
    cacheable = True

    def __init__(self, name: str, warehouse: str,
                 writer_target_bytes: int = 32 << 20,
                 injector=None):
        self.name = name
        self.warehouse = warehouse
        self.writer_target_bytes = writer_target_bytes
        self.fs = LocalObjectStore(warehouse, injector=injector)
        self._metadata = HiveMetadata(warehouse, connector=self,
                                      fs=self.fs)

    def data_version(self, table: Optional[str] = None) -> int:
        """Fingerprint of (path, mtime_ns, ctime_ns, inode, size) per
        file.  With a table, only that table's directory is walked — so
        queries don't stat the whole warehouse and a write to table A
        never invalidates B's cached scans or compiled fragments.  The
        inode + ctime terms catch same-size in-place rewrites even on
        filesystems with coarse mtime granularity (an atomic
        rename-into-place always changes the inode).  The digest is
        process-stable (blake2b, not salted hash()) — persistent
        compile-cache keys embed it and must survive restarts.

        Listing goes through the object store (which skips dotfiles, so
        the ANALYZE sidecar never invalidates the version it is keyed
        by); the inode/ctime terms come from a local stat via the
        ``local_path()`` escape hatch."""
        h = hashlib.blake2b(digest_size=8)
        for e in self.fs.list_files(table or ""):
            try:
                st = os.stat(self.fs.local_path(e.path))
            except OSError:
                continue
            h.update(
                repr((e.path, st.st_mtime_ns, st.st_ctime_ns, st.st_ino,
                      st.st_size)).encode()
            )
        return int.from_bytes(h.digest(), "little")

    def metadata(self) -> HiveMetadata:
        return self._metadata

    def split_manager(self) -> HiveSplitManager:
        return HiveSplitManager(self._metadata, self)

    def session_property_metadata(self):
        from ..config import PropertyMetadata, _bool

        return {
            "row_group_pruning": PropertyMetadata(
                "row_group_pruning",
                "prune parquet row groups from footer min/max stats",
                _bool, True,
            ),
        }

    def page_source_provider(self) -> HivePageSourceProvider:
        return HivePageSourceProvider()

    def page_sink_provider(self) -> HivePageSinkProvider:
        return HivePageSinkProvider(self)


class HivePageSink(PageSink):
    """SCALED parquet writer (ScaledWriterScheduler +
    ScaleWriterPartitioningExchanger roles, collapsed to the local sink):
    appended pages buffer host-side; finish() sizes the writer pool from
    the OBSERVED data volume — one part file per `writer_target_bytes`
    of input, up to `max_writers` — and writes the parts on parallel
    threads.  Rows route through the SkewedPartitionRebalancer on the
    leading column: same-valued rows cluster into the same part file
    (better scan locality + row-group stats), but a HOT value's rows
    spread across extra writers so no writer stalls on skew — exactly
    the ScaleWriterPartitioningExchanger contract (clustering is a
    preference, balance is enforced)."""

    def __init__(self, warehouse: str, table: str, columns, overwrite: bool,
                 writer_target_bytes: int = 32 << 20,
                 max_writers: int = 8,
                 fs: Optional[LocalObjectStore] = None):
        self.warehouse = warehouse
        self.fs = fs if fs is not None else LocalObjectStore(warehouse)
        self.table = table
        self.columns = list(columns)
        self.overwrite = overwrite
        self.writer_target_bytes = writer_target_bytes
        self.max_writers = max_writers
        self.pages: List[Page] = []
        self.bytes = 0
        self.writers_used = 0

    def append(self, page: Page) -> None:
        self.pages.append(page)
        for c in page.columns:
            self.bytes += int(np.asarray(c.values)[: page.count].nbytes)

    def finish(self) -> int:
        from ..exec.partitioner import concat_pages, take_rows

        if self.overwrite:
            for e in self.fs.list_files(self.table):
                if e.path.endswith(".parquet"):
                    self.fs.delete_file(e.path)
        if not self.pages:
            self.writers_used = 0
            return 0
        page = concat_pages(self.pages)
        page = Page(page.columns, page.count, self.columns)
        nwriters = max(
            1, min(self.max_writers, -(-self.bytes // self.writer_target_bytes))
        )
        self.writers_used = nwriters
        import threading
        import time as _time

        stamp = f"{int(_time.time() * 1e6):x}"
        if nwriters == 1:
            write_parquet_table(
                self.warehouse, self.table, page,
                file_name=f"part-{stamp}-0.parquet", fs=self.fs,
            )
            return page.count
        from ..exec.partitioner import SkewedPartitionRebalancer

        reb = SkewedPartitionRebalancer(nwriters)
        parts = reb.partition_page(page, [self.columns[0]])
        self.rebalancer = reb
        errors: List[BaseException] = []

        def write_part(w: int):
            try:
                sub = parts[w]
                if sub.count == 0:
                    return
                write_parquet_table(
                    self.warehouse, self.table, sub,
                    file_name=f"part-{stamp}-{w}.parquet", fs=self.fs,
                )
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [
            threading.Thread(target=write_part, args=(w,))
            for w in range(nwriters)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return page.count


class HivePageSinkProvider(PageSinkProvider):
    def __init__(self, connector: "HiveConnector"):
        self.connector = connector

    def create_sink(self, table: str, columns, overwrite: bool = False):
        return HivePageSink(
            self.connector.warehouse, table, columns, overwrite,
            writer_target_bytes=self.connector.writer_target_bytes,
            fs=self.connector.fs,
        )


class HiveConnectorFactory(ConnectorFactory):
    """Reference: HiveConnectorFactory — config key hive.warehouse-dir."""

    name = "hive"

    def create(self, catalog_name: str, config: dict) -> HiveConnector:
        warehouse = config.get("hive.warehouse-dir")
        if not warehouse:
            raise ValueError("hive catalog requires hive.warehouse-dir")
        injector = None
        spec = config.get("hive.fault-injection")
        if spec:
            from ..utils.faults import FaultInjector

            injector = FaultInjector.from_spec(spec)
        return HiveConnector(
            catalog_name, warehouse,
            writer_target_bytes=int(
                config.get("hive.writer-target-bytes", 32 << 20)
            ),
            injector=injector,
        )


def write_parquet_table(
    warehouse: str,
    table: str,
    page: Page,
    rows_per_group: int = 100_000,
    file_name: str = "part-0.parquet",
    fs: Optional[LocalObjectStore] = None,
):
    """Write a Page as a parquet table file (TableWriter role for tests and
    CTAS into hive catalogs).  Serializes to a buffer and PUTs through
    the object store so the write is atomic and fault-injectable."""
    _require_pyarrow()
    arrays = []
    names = page.names or [f"c{i}" for i in range(page.num_columns)]
    for col in page.columns:
        vals = col.to_python(page.count)
        t = col.type
        if t.is_dictionary:
            arrays.append(pa.array(vals, pa.string()))
        elif t.is_decimal:
            import decimal as _d

            q = _d.Decimal(1).scaleb(-t.scale)
            arrays.append(
                pa.array(
                    [None if v is None else _d.Decimal(str(v)).quantize(q)
                     for v in vals],
                    pa.decimal128(t.precision, t.scale),
                )
            )
        elif t.name == "date":
            arrays.append(
                pa.array(
                    [None if v is None else str(v) for v in vals],
                ).cast(pa.date32())
            )
        elif t.name == "timestamp":
            raw = np.asarray(col.values)[: page.count]
            mask = (
                None
                if col.validity is None
                else ~np.asarray(col.validity)[: page.count]
            )
            arrays.append(
                pa.array(raw, pa.timestamp("us"), mask=mask)
            )
        else:
            arrays.append(pa.array(vals))
    tbl = pa.table(dict(zip(names, arrays)))
    store = fs if fs is not None else LocalObjectStore(warehouse)
    buf = io.BytesIO()
    pq.write_table(tbl, buf, row_group_size=rows_per_group)
    store.write_file(f"{table}/{file_name}", buf.getvalue())
