"""ctypes bindings + build for the native (C++) TPC-H generator.

The shared library is built on first use with g++ -O3 (cached under
native/build/).  Falls back silently to the numpy path when a toolchain
is unavailable; results are bit-identical either way (tested).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "tpchgen.cpp")
_SO = os.path.join(_ROOT, "native", "build", "libtpchgen.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    try:
        if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            os.makedirs(os.path.dirname(_SO), exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
        lib.gen_lineitem.restype = ctypes.c_int64
        _LIB = lib
    except Exception:
        _LIB = None
    return _LIB


_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_U64P = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")

LINEITEM_COLS = [
    "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber", "l_quantity",
    "l_extendedprice", "l_discount", "l_tax", "l_shipdate", "l_commitdate",
    "l_receiptdate", "l_returnflag", "l_linestatus", "l_shipinstruct",
    "l_shipmode", "l_comment",
]

_BASE_KEYS = [
    "l_count", "o_orderdate", "l_shipdate", "l_partkey", "l_supp_slot",
    "l_quantity", "l_discount", "l_tax", "l_commitdate", "l_receiptdate",
    "l_returnflag", "l_shipinstruct", "l_shipmode", "l_comment", "o_custkey",
]


def available() -> bool:
    return _load() is not None


def gen_lineitem(
    lo_order: int, hi_order: int, npart: int, nsupp: int, ncomments: int
) -> Optional[Dict[str, np.ndarray]]:
    """All 16 lineitem columns for orders [lo, hi), or None if no lib."""
    lib = _load()
    if lib is None:
        return None
    from .tpch import _fnv

    bases = np.array([np.uint64(_fnv(k)) for k in _BASE_KEYS], dtype=np.uint64)
    cap = 7 * max(1, hi_order - lo_order)
    i64 = {
        c: np.empty(cap, dtype=np.int64)
        for c in ("l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
                  "l_quantity", "l_extendedprice", "l_discount", "l_tax")
    }
    i32 = {
        c: np.empty(cap, dtype=np.int32)
        for c in ("l_shipdate", "l_commitdate", "l_receiptdate",
                  "l_returnflag", "l_linestatus", "l_shipinstruct",
                  "l_shipmode", "l_comment")
    }
    n = lib.gen_lineitem(
        ctypes.c_int64(lo_order), ctypes.c_int64(hi_order),
        ctypes.c_int64(npart), ctypes.c_int64(nsupp),
        ctypes.c_int64(ncomments),
        bases.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) for a in (
            i64["l_orderkey"], i64["l_partkey"], i64["l_suppkey"],
            i64["l_linenumber"], i64["l_quantity"], i64["l_extendedprice"],
            i64["l_discount"], i64["l_tax"],
        )],
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)) for a in (
            i32["l_shipdate"], i32["l_commitdate"], i32["l_receiptdate"],
            i32["l_returnflag"], i32["l_linestatus"], i32["l_shipinstruct"],
            i32["l_shipmode"], i32["l_comment"],
        )],
    )
    out: Dict[str, np.ndarray] = {}
    for c, a in {**i64, **i32}.items():
        out[c] = a[:n]
    return out
