"""On-device (TPU) TPC-H column generation.

Reference parity: plugin/trino-tpch generates rows IN-PROCESS during the
scan (TpchPageSourceProvider streams generator output straight into the
operator pipeline) — the data never exists anywhere else.  The TPU-native
analog generates columns directly in HBM: the connector's counter-based
design (tpch.py: every attribute is a pure function of (table, column,
row-index) via the splitmix64 finalizer) is exactly a device kernel, so
a scan's arrays materialize on-chip from a seed + row range with ZERO
host datagen and ZERO host->device transfer.

This is the scan path's equivalent of the reference's in-process
generation, not a benchmark shortcut: the QUERY program is unchanged (it
reads the same padded HBM lanes the upload path would have produced, and
the jit cache keys are identical); only the producer of those lanes
moved from numpy+PCIe/tunnel to an XLA program.  Exact bit-parity with
the host generator is enforced by tests/test_tpch_device.py (splitmix64
is pure integer math: jnp.uint64 and np.uint64 agree exactly).

Columns whose host path formats per-row strings (names, phones,
addresses, clerks) are not device-generatable; a scan touching one falls
back to the host generator wholesale.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import tpch as H

# ---------------------------------------------------------------------
# splitmix64 core (jnp port of tpch.mix64 / h64 / uint_in — python-int
# constants converted at trace time; module-level jnp constants would
# become hidden const args, which the axon tunnel corrupts on
# re-dispatch, see ops/int128.py)

_M_GOLD = 0x9E3779B97F4A7C15
_M_B = 0xBF58476D1CE4E5B9
_M_C = 0x94D049BB133111EB


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = x + jnp.uint64(_M_GOLD)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(_M_B)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(_M_C)
    return x ^ (x >> jnp.uint64(31))


def _h64(key: str, idx: jnp.ndarray, salt: int = 0) -> jnp.ndarray:
    base = int(H._fnv(key)) ^ (salt * _M_GOLD & 0xFFFFFFFFFFFFFFFF)
    return _mix64(idx.astype(jnp.uint64) ^ jnp.uint64(base))


def _uint_in(key: str, idx, lo: int, hi: int, salt: int = 0) -> jnp.ndarray:
    return (
        _h64(key, idx, salt) % jnp.uint64(hi - lo + 1)
    ).astype(jnp.int64) + lo


def _orderkey(j: jnp.ndarray) -> jnp.ndarray:
    return (j // 8) * 32 + (j % 8) + 1


def _custkey_for_order(j: jnp.ndarray, ncust: int) -> jnp.ndarray:
    usable = ncust - ncust // 3
    i = (_h64("o_custkey", j) % jnp.uint64(max(1, usable))).astype(jnp.int64)
    return 3 * (i // 2) + 1 + (i % 2)


def _retail_price_cents(partkey: jnp.ndarray) -> jnp.ndarray:
    return 90000 + (partkey // 10) % 20001 + 100 * (partkey % 1000)


def _ps_suppkey(partkey: jnp.ndarray, i, nsupp: int) -> jnp.ndarray:
    return (partkey + i * (nsupp // 4 + (partkey - 1) // nsupp)) % nsupp + 1


def _line_count(j: jnp.ndarray) -> jnp.ndarray:
    return 1 + (_h64("l_count", j) % jnp.uint64(7)).astype(jnp.int64)


# ---------------------------------------------------------------------
# per-table device column generators.  Each returns values for rows
# [lo, lo+cap) masked so rows >= hi produce 0 (mirroring the host path's
# zero padding); `lo`/`hi` are TRACED scalars so every streaming tile of
# the same padded shape shares one compiled generator.

# columns the device path can produce (everything except host-formatted
# lazy strings); comments/names with fixed vocabularies are dict CODES
DEVICE_COLS: Dict[str, frozenset] = {
    "region": frozenset({"r_regionkey", "r_name", "r_comment"}),
    "nation": frozenset(
        {"n_nationkey", "n_name", "n_regionkey", "n_comment"}
    ),
    "supplier": frozenset(
        {"s_suppkey", "s_nationkey", "s_acctbal", "s_comment"}
    ),
    "customer": frozenset(
        {"c_custkey", "c_nationkey", "c_acctbal", "c_mktsegment",
         "c_comment"}
    ),
    "part": frozenset(
        {"p_partkey", "p_mfgr", "p_brand", "p_type", "p_size",
         "p_container", "p_retailprice", "p_comment"}
    ),
    "partsupp": frozenset(
        {"ps_partkey", "ps_suppkey", "ps_availqty", "ps_supplycost",
         "ps_comment"}
    ),
    "orders": frozenset(
        {"o_orderkey", "o_custkey", "o_orderstatus", "o_totalprice",
         "o_orderdate", "o_orderpriority", "o_shippriority", "o_comment"}
    ),
    "lineitem": frozenset(
        {"l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
         "l_quantity", "l_extendedprice", "l_discount", "l_tax",
         "l_returnflag", "l_linestatus", "l_shipdate", "l_commitdate",
         "l_receiptdate", "l_shipinstruct", "l_shipmode", "l_comment"}
    ),
}

_NCOMMENT = len(H.COMMENTS)


def _dict_code(key: str, idx, n: int) -> jnp.ndarray:
    return (_h64(key, idx) % jnp.uint64(n)).astype(jnp.int32)


def _base_table(table: str, cols, idx, n: Dict[str, int], sf: float):
    """Columns for non-lineitem tables at order/global index `idx`."""
    out: Dict[str, jnp.ndarray] = {}
    key = idx.astype(jnp.int64) + 1
    for c in cols:
        if c in ("r_regionkey", "n_nationkey"):
            out[c] = idx.astype(jnp.int64)
        elif c in ("r_name", "n_name"):
            out[c] = idx.astype(jnp.int32)
        elif c == "n_regionkey":
            region_of = jnp.asarray(
                np.array([r for _, r in H.NATIONS], dtype=np.int64)
            )
            out[c] = region_of[jnp.clip(idx, 0, len(H.NATIONS) - 1)]
        elif c in ("s_suppkey", "c_custkey", "p_partkey"):
            out[c] = key
        elif c in ("s_nationkey", "c_nationkey"):
            out[c] = _uint_in(c, idx, 0, 24)
        elif c in ("s_acctbal", "c_acctbal"):
            out[c] = _uint_in(c, idx, -99999, 999999)
        elif c == "c_mktsegment":
            out[c] = _dict_code(c, idx, 5)
        elif c == "p_mfgr":
            out[c] = _dict_code("p_mfgr", idx, 5)
        elif c == "p_brand":
            m = (_h64("p_mfgr", idx) % jnp.uint64(5)).astype(jnp.int64)
            b = (_h64("p_brand", idx) % jnp.uint64(5)).astype(jnp.int64)
            out[c] = (m * 5 + b).astype(jnp.int32)
        elif c == "p_type":
            out[c] = _dict_code(c, idx, len(H.P_TYPES))
        elif c == "p_size":
            out[c] = _uint_in(c, idx, 1, 50)
        elif c == "p_container":
            out[c] = _dict_code(c, idx, len(H.CONTAINERS))
        elif c == "p_retailprice":
            out[c] = _retail_price_cents(key)
        elif c == "ps_partkey":
            out[c] = (idx // 4).astype(jnp.int64) + 1
        elif c == "ps_suppkey":
            p = (idx // 4).astype(jnp.int64) + 1
            out[c] = _ps_suppkey(p, (idx % 4).astype(jnp.int64),
                                 n["supplier"])
        elif c == "ps_availqty":
            out[c] = _uint_in(c, idx, 1, 9999)
        elif c == "ps_supplycost":
            out[c] = _uint_in(c, idx, 100, 100000)
        elif c == "o_orderkey":
            out[c] = _orderkey(idx.astype(jnp.int64))
        elif c == "o_custkey":
            out[c] = _custkey_for_order(idx.astype(jnp.int64), n["customer"])
        elif c == "o_orderdate":
            out[c] = (
                H.EPOCH_1992
                + _uint_in("o_orderdate", idx, 0, H.ORDER_DATE_SPAN - 1)
            ).astype(jnp.int32)
        elif c == "o_totalprice":
            out[c] = _uint_in(c, idx, 100000, 50000000)
        elif c == "o_orderpriority":
            out[c] = _dict_code(c, idx, 5)
        elif c == "o_shippriority":
            out[c] = jnp.zeros(idx.shape[0], dtype=jnp.int64)
        elif c == "o_orderstatus":
            j = idx.astype(jnp.int64)
            odate = H.EPOCH_1992 + _uint_in(
                "o_orderdate", j, 0, H.ORDER_DATE_SPAN - 1
            )
            counts = _line_count(j)
            all_f = jnp.ones(j.shape[0], dtype=bool)
            all_o = jnp.ones(j.shape[0], dtype=bool)
            for ln in range(7):
                has = counts > ln
                ship = odate + 1 + (
                    _h64("l_shipdate", j * jnp.int64(8) + ln)
                    % jnp.uint64(121)
                ).astype(jnp.int64)
                f = ship <= H.CURRENT_DATE
                all_f &= ~has | f
                all_o &= ~has | ~f
            out[c] = jnp.where(
                all_f, 0, jnp.where(all_o, 1, 2)
            ).astype(jnp.int32)
        elif c.endswith("_comment"):
            out[c] = _dict_code(c, idx, _NCOMMENT)
        else:  # pragma: no cover — guarded by DEVICE_COLS
            raise KeyError(c)
    return out


def _lineitem(cols, oj, ln, n: Dict[str, int]):
    """Lineitem columns at (order index oj, line number ln)."""
    lid = oj * jnp.int64(8) + ln
    out: Dict[str, jnp.ndarray] = {}
    odate = H.EPOCH_1992 + _uint_in("o_orderdate", oj, 0,
                                    H.ORDER_DATE_SPAN - 1)
    ship = odate + 1 + (
        _h64("l_shipdate", lid) % jnp.uint64(121)
    ).astype(jnp.int64)
    partkey = 1 + (
        _h64("l_partkey", lid) % jnp.uint64(n["part"])
    ).astype(jnp.int64)
    qty = _uint_in("l_quantity", lid, 1, 50)
    for c in cols:
        if c == "l_orderkey":
            out[c] = _orderkey(oj)
        elif c == "l_partkey":
            out[c] = partkey
        elif c == "l_suppkey":
            slot = (_h64("l_supp_slot", lid) % jnp.uint64(4)).astype(
                jnp.int64
            )
            out[c] = _ps_suppkey(partkey, slot, n["supplier"])
        elif c == "l_linenumber":
            out[c] = ln + 1
        elif c == "l_quantity":
            out[c] = qty * 100
        elif c == "l_extendedprice":
            out[c] = qty * _retail_price_cents(partkey)
        elif c == "l_discount":
            out[c] = _uint_in(c, lid, 0, 10)
        elif c == "l_tax":
            out[c] = _uint_in(c, lid, 0, 8)
        elif c == "l_shipdate":
            out[c] = ship.astype(jnp.int32)
        elif c == "l_commitdate":
            out[c] = (odate + _uint_in(c, lid, 30, 90)).astype(jnp.int32)
        elif c == "l_receiptdate":
            out[c] = (ship + _uint_in(c, lid, 1, 30)).astype(jnp.int32)
        elif c == "l_returnflag":
            receipt = ship + _uint_in("l_receiptdate", lid, 1, 30)
            rnd = (_h64(c, lid) % jnp.uint64(2)).astype(jnp.int32)
            out[c] = jnp.where(
                receipt <= H.CURRENT_DATE, rnd * 2, 1
            ).astype(jnp.int32)
        elif c == "l_linestatus":
            out[c] = (ship > H.CURRENT_DATE).astype(jnp.int32)
        elif c == "l_shipinstruct":
            out[c] = _dict_code(c, lid, 4)
        elif c == "l_shipmode":
            out[c] = _dict_code(c, lid, 7)
        elif c == "l_comment":
            out[c] = _dict_code(c, lid, _NCOMMENT)
        else:  # pragma: no cover
            raise KeyError(c)
    return out


# ---------------------------------------------------------------------
# traced entry points (jitted once per (table, cols, caps, sf); lo/hi
# ride as traced scalars so all same-shape tiles share one executable)

_JIT_CACHE: Dict[tuple, object] = {}


def clear_jit_cache() -> int:
    """Drop every compiled generator executable.  Called by the executor
    on poisoned-executable eviction and on CPU-fallback entry: these
    executables are bound to the faulted device, and this module-level
    cache was exempt from the executor's jit-cache eviction until the
    BENCH_r05 crash traced back to a re-dispatched stale generator."""
    n = len(_JIT_CACHE)
    _JIT_CACHE.clear()
    return n


def _gen_flat(table: str, cols: tuple, cap: int, sf: float):
    n = H._counts(sf)

    def fn(lo, hi):
        idx = lo + jnp.arange(cap, dtype=jnp.int64)
        live = idx < hi
        idx = jnp.where(live, idx, 0)
        vals = _base_table(table, cols, idx, n, sf)
        return {
            c: jnp.where(live, v, jnp.zeros((), v.dtype))
            for c, v in vals.items()
        }

    # no-donate: generator args are two scalars (lo, hi); lanes are outputs
    return jax.jit(fn)


def _gen_lineitem(cols: tuple, cap_orders: int, cap_rows: int, sf: float):
    n = H._counts(sf)

    def fn(lo, hi):
        j = lo + jnp.arange(cap_orders, dtype=jnp.int64)
        jlive = j < hi
        counts = jnp.where(jlive, _line_count(jnp.where(jlive, j, 0)), 0)
        cum = jnp.cumsum(counts)  # cum[k] = lines of orders lo..lo+k
        total = cum[-1] if cap_orders else jnp.int64(0)
        r = jnp.arange(cap_rows, dtype=jnp.int64)
        live = r < total
        # order slot of each output row: first k with cum[k] > r
        slot = jnp.searchsorted(cum, r, side="right").astype(jnp.int64)
        slot = jnp.clip(slot, 0, max(cap_orders - 1, 0))
        starts = cum - counts
        oj = jnp.where(live, lo + slot, 0)
        ln = jnp.where(live, r - starts[slot], 0)
        vals = _lineitem(cols, oj, ln, n)
        return {
            c: jnp.where(live, v, jnp.zeros((), v.dtype))
            for c, v in vals.items()
        }

    # no-donate: generator args are two scalars (lo, hi); lanes are outputs
    return jax.jit(fn)


def supports(table: str, cols: Sequence[str]) -> bool:
    dev = DEVICE_COLS.get(table)
    return dev is not None and all(c in dev for c in cols)


def device_lanes(
    table: str,
    cols: Sequence[str],
    lo: int,
    hi: int,
    cap: int,
    sf: float,
    count: int,
    cap_orders: Optional[int] = None,
) -> Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Generate the padded device lanes for rows of `table` whose
    (order-)index lies in [lo, hi).  `cap` is the padded row capacity;
    `count` the exact live row count (host-computed for lineitem);
    `cap_orders` a STATIC upper bound on hi-lo (padded so streaming
    tiles whose spans differ by a few rows share one executable)."""
    cols = tuple(cols)
    if table == "lineitem":
        if cap_orders is None:
            cap_orders = int(hi - lo)
        key = (table, cols, cap_orders, cap, sf)
        fn = _JIT_CACHE.get(key)
        if fn is None:
            fn = _JIT_CACHE[key] = _gen_lineitem(cols, cap_orders, cap, sf)
    else:
        key = (table, cols, cap, sf)
        fn = _JIT_CACHE.get(key)
        if fn is None:
            fn = _JIT_CACHE[key] = _gen_flat(table, cols, cap, sf)
    vals = fn(jnp.int64(lo), jnp.int64(hi))
    ok = jnp.ones(cap, dtype=bool)
    return {c: (vals[c], ok) for c in cols}


def lineitem_count(lo: int, hi: int) -> int:
    """Exact line rows for orders [lo, hi) — host-side numpy (the cheap
    1-hash-per-order part of generation; columns stay on device)."""
    j = np.arange(lo, hi, dtype=np.int64)
    return int(
        (1 + (H.h64("l_count", j) % np.uint64(7)).astype(np.int64)).sum()
    )
