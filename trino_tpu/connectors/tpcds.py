"""TPC-DS generator connector (subset).

Reference parity: plugin/trino-tpcds (TpcdsConnectorFactory, TpcdsMetadata,
TpcdsSplitManager/TpcdsRecordSetProvider over io.trino.tpcds dsdgen).

Same counter-based (splitmix64) design as the tpch connector: every
attribute is a pure function of (table, column, row index), vectorized in
numpy; splits generate independently.  Covers the star-schema tables used
by the driver benchmark configs (TPC-DS Q3/Q7) and common derived queries:
store_sales + date_dim, item, customer_demographics, promotion, store.

Unlike TPC-H, TPC-DS fact-table foreign keys are nullable (~4%), which
exercises the engine's null-key join semantics.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)
from .tpch import h64, mix64, uint_in

DEC = T.decimal(7, 2)

GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = [
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
]
CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry", "Men",
    "Music", "Shoes", "Sports", "Women",
]
CLASSES = [f"class#{i}" for i in range(1, 17)]
YN = ["N", "Y"]

DATE_DIM_ROWS = 73049  # 1900-01-02 .. 2100-01-01 (dsdgen fixed)
DATE_SK_BASE = 2415022  # julian day of 1900-01-02
EPOCH_OFFSET = -25567  # days from 1970-01-01 back to 1900-01-02


def _counts(sf: float) -> Dict[str, int]:
    """dsdgen cardinalities (TPC-DS spec table 3-2): facts scale
    linearly, dimensions by ~sf^(1/2..2/3), several are fixed."""
    dim = max(1.0, sf) ** 0.5
    return {
        "date_dim": DATE_DIM_ROWS,
        "time_dim": 86_400,
        "item": max(10, int(18_000 * dim)),
        "store": max(2, int(12 * dim)),
        "promotion": max(5, int(300 * dim)),
        "warehouse": max(1, int(5 * dim)),
        "ship_mode": 20,
        "reason": max(5, int(35 * dim)),
        "income_band": 20,
        "household_demographics": 7_200,
        "customer_demographics": 1_920_800 if sf >= 0.1 else 19_208,
        "customer": max(
            100,
            int(100_000 * (sf ** (2.0 / 3.0) if sf >= 1 else sf)),
        ),
        "customer_address": max(
            50,
            int(50_000 * (sf ** (2.0 / 3.0) if sf >= 1 else sf)),
        ),
        "store_sales": max(10, int(2_880_404 * sf)),
        "catalog_sales": max(10, int(1_441_548 * sf)),
        "web_sales": max(10, int(719_384 * sf)),
    }


SCHEMAS: Dict[str, List[Tuple[str, T.Type]]] = {
    "date_dim": [
        ("d_date_sk", T.BIGINT),
        ("d_date", T.DATE),
        ("d_year", T.BIGINT),
        ("d_moy", T.BIGINT),
        ("d_dom", T.BIGINT),
        ("d_qoy", T.BIGINT),
    ],
    "item": [
        ("i_item_sk", T.BIGINT),
        ("i_item_id", T.VARCHAR),
        ("i_brand_id", T.BIGINT),
        ("i_brand", T.VARCHAR),
        ("i_manufact_id", T.BIGINT),
        ("i_manager_id", T.BIGINT),
        ("i_category_id", T.BIGINT),
        ("i_category", T.VARCHAR),
        ("i_class_id", T.BIGINT),
        ("i_class", T.VARCHAR),
        ("i_current_price", DEC),
    ],
    "store": [
        ("s_store_sk", T.BIGINT),
        ("s_store_id", T.VARCHAR),
        ("s_store_name", T.VARCHAR),
        ("s_number_employees", T.BIGINT),
        ("s_city", T.VARCHAR),
        ("s_county", T.VARCHAR),
        ("s_state", T.VARCHAR),
        ("s_gmt_offset", T.decimal(5, 2)),
    ],
    "promotion": [
        ("p_promo_sk", T.BIGINT),
        ("p_promo_id", T.VARCHAR),
        ("p_channel_email", T.VARCHAR),
        ("p_channel_event", T.VARCHAR),
    ],
    "customer_demographics": [
        ("cd_demo_sk", T.BIGINT),
        ("cd_gender", T.VARCHAR),
        ("cd_marital_status", T.VARCHAR),
        ("cd_education_status", T.VARCHAR),
    ],
    "store_sales": [
        ("ss_sold_date_sk", T.BIGINT),
        ("ss_sold_time_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT),
        ("ss_customer_sk", T.BIGINT),
        ("ss_cdemo_sk", T.BIGINT),
        ("ss_hdemo_sk", T.BIGINT),
        ("ss_addr_sk", T.BIGINT),
        ("ss_store_sk", T.BIGINT),
        ("ss_promo_sk", T.BIGINT),
        ("ss_ticket_number", T.BIGINT),
        ("ss_quantity", T.BIGINT),
        ("ss_wholesale_cost", DEC),
        ("ss_list_price", DEC),
        ("ss_sales_price", DEC),
        ("ss_ext_sales_price", DEC),
        ("ss_ext_discount_amt", DEC),
        ("ss_ext_wholesale_cost", DEC),
        ("ss_ext_list_price", DEC),
        ("ss_coupon_amt", DEC),
        ("ss_net_paid", DEC),
        ("ss_net_profit", DEC),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", T.BIGINT),
        ("cs_sold_time_sk", T.BIGINT),
        ("cs_ship_date_sk", T.BIGINT),
        ("cs_bill_customer_sk", T.BIGINT),
        ("cs_bill_cdemo_sk", T.BIGINT),
        ("cs_bill_hdemo_sk", T.BIGINT),
        ("cs_bill_addr_sk", T.BIGINT),
        ("cs_ship_mode_sk", T.BIGINT),
        ("cs_warehouse_sk", T.BIGINT),
        ("cs_item_sk", T.BIGINT),
        ("cs_promo_sk", T.BIGINT),
        ("cs_order_number", T.BIGINT),
        ("cs_quantity", T.BIGINT),
        ("cs_wholesale_cost", DEC),
        ("cs_list_price", DEC),
        ("cs_sales_price", DEC),
        ("cs_ext_sales_price", DEC),
        ("cs_ext_discount_amt", DEC),
        ("cs_coupon_amt", DEC),
        ("cs_net_paid", DEC),
        ("cs_net_profit", DEC),
    ],
    "web_sales": [
        ("ws_sold_date_sk", T.BIGINT),
        ("ws_sold_time_sk", T.BIGINT),
        ("ws_ship_date_sk", T.BIGINT),
        ("ws_item_sk", T.BIGINT),
        ("ws_bill_customer_sk", T.BIGINT),
        ("ws_bill_cdemo_sk", T.BIGINT),
        ("ws_bill_hdemo_sk", T.BIGINT),
        ("ws_bill_addr_sk", T.BIGINT),
        ("ws_web_page_sk", T.BIGINT),
        ("ws_warehouse_sk", T.BIGINT),
        ("ws_promo_sk", T.BIGINT),
        ("ws_order_number", T.BIGINT),
        ("ws_quantity", T.BIGINT),
        ("ws_wholesale_cost", DEC),
        ("ws_list_price", DEC),
        ("ws_sales_price", DEC),
        ("ws_ext_sales_price", DEC),
        ("ws_ext_discount_amt", DEC),
        ("ws_coupon_amt", DEC),
        ("ws_net_paid", DEC),
        ("ws_net_profit", DEC),
    ],
    "customer": [
        ("c_customer_sk", T.BIGINT),
        ("c_customer_id", T.VARCHAR),
        ("c_current_cdemo_sk", T.BIGINT),
        ("c_current_hdemo_sk", T.BIGINT),
        ("c_current_addr_sk", T.BIGINT),
        ("c_first_name", T.VARCHAR),
        ("c_last_name", T.VARCHAR),
        ("c_preferred_cust_flag", T.VARCHAR),
        ("c_birth_year", T.BIGINT),
        ("c_birth_month", T.BIGINT),
        ("c_birth_country", T.VARCHAR),
        ("c_email_address", T.VARCHAR),
        ("c_first_sales_date_sk", T.BIGINT),
        ("c_first_shipto_date_sk", T.BIGINT),
    ],
    "customer_address": [
        ("ca_address_sk", T.BIGINT),
        ("ca_address_id", T.VARCHAR),
        ("ca_street_number", T.VARCHAR),
        ("ca_city", T.VARCHAR),
        ("ca_county", T.VARCHAR),
        ("ca_state", T.VARCHAR),
        ("ca_zip", T.VARCHAR),
        ("ca_country", T.VARCHAR),
        ("ca_gmt_offset", T.decimal(5, 2)),
        ("ca_location_type", T.VARCHAR),
    ],
    "household_demographics": [
        ("hd_demo_sk", T.BIGINT),
        ("hd_income_band_sk", T.BIGINT),
        ("hd_buy_potential", T.VARCHAR),
        ("hd_dep_count", T.BIGINT),
        ("hd_vehicle_count", T.BIGINT),
    ],
    "time_dim": [
        ("t_time_sk", T.BIGINT),
        ("t_time_id", T.VARCHAR),
        ("t_time", T.BIGINT),
        ("t_hour", T.BIGINT),
        ("t_minute", T.BIGINT),
        ("t_second", T.BIGINT),
        ("t_am_pm", T.VARCHAR),
        ("t_meal_time", T.VARCHAR),
    ],
    "warehouse": [
        ("w_warehouse_sk", T.BIGINT),
        ("w_warehouse_name", T.VARCHAR),
        ("w_warehouse_sq_ft", T.BIGINT),
        ("w_city", T.VARCHAR),
        ("w_state", T.VARCHAR),
        ("w_country", T.VARCHAR),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", T.BIGINT),
        ("sm_ship_mode_id", T.VARCHAR),
        ("sm_type", T.VARCHAR),
        ("sm_carrier", T.VARCHAR),
    ],
    "reason": [
        ("r_reason_sk", T.BIGINT),
        ("r_reason_id", T.VARCHAR),
        ("r_reason_desc", T.VARCHAR),
    ],
    "income_band": [
        ("ib_income_band_sk", T.BIGINT),
        ("ib_lower_bound", T.BIGINT),
        ("ib_upper_bound", T.BIGINT),
    ],
}

# dsdgen value domains for the columns the benchmark queries test
# (TPC-DS spec appendix: cities/buy-potential/meal-times are the
# highest-frequency dsdgen values the published queries filter on)
BUY_POTENTIAL = [
    "0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown",
]
CITIES = [
    "Midway", "Fairview", "Oak Grove", "Five Points", "Oakland",
    "Riverside", "Sunnyside", "Bethel", "Pleasant Hill", "Centerville",
    "Liberty", "Salem", "Union", "Greenville", "Franklin", "Springdale",
    "Glendale", "Marion", "Highland", "Antioch",
]
STATES = [
    "AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA", "HI",
    "ID", "IL", "IN", "IA", "KS", "KY", "LA", "ME", "MD", "MA", "MI",
    "MN", "MS", "MO", "MT", "NE", "NV", "NH", "NJ", "NM", "NY", "NC",
    "ND", "OH", "OK", "OR", "PA", "RI", "SC", "SD", "TN", "TX", "UT",
    "VT", "VA", "WA", "WV", "WI", "WY",
]
COUNTRIES = [
    "United States", "Canada", "Mexico", "Germany", "France", "Japan",
    "United Kingdom", "Brazil", "India", "China",
]
FIRST_NAMES = [
    "James", "Mary", "John", "Patricia", "Robert", "Jennifer",
    "Michael", "Linda", "William", "Elizabeth", "David", "Barbara",
    "Richard", "Susan", "Joseph", "Jessica", "Thomas", "Sarah",
]
LAST_NAMES = [
    "Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
    "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez", "Lopez",
    "Gonzalez", "Wilson", "Anderson", "Thomas", "Taylor", "Moore",
]
MEALS = ["breakfast", "lunch", "dinner"]
AMPM = ["AM", "PM"]
SHIP_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"]
CARRIERS = [
    "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "MSC",
    "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES", "ZOUROS",
    "GERMA", "DIAMOND", "RUPEKSA", "GREAT EASTERN", "HARMSTORF", "PRIVATECARRIER",
]

_VOCABS = {
    "cd_gender": np.array(GENDERS, dtype=object),
    "cd_marital_status": np.array(MARITAL, dtype=object),
    "cd_education_status": np.array(EDUCATION, dtype=object),
    "i_category": np.array(CATEGORIES, dtype=object),
    "i_class": np.array(CLASSES, dtype=object),
    "p_channel_email": np.array(YN, dtype=object),
    "p_channel_event": np.array(YN, dtype=object),
    "hd_buy_potential": np.array(BUY_POTENTIAL, dtype=object),
    "ca_city": np.array(CITIES, dtype=object),
    "ca_state": np.array(STATES, dtype=object),
    "ca_country": np.array(COUNTRIES[:1], dtype=object),
    "c_birth_country": np.array(COUNTRIES, dtype=object),
    "c_first_name": np.array(FIRST_NAMES, dtype=object),
    "c_last_name": np.array(LAST_NAMES, dtype=object),
    "c_preferred_cust_flag": np.array(YN, dtype=object),
    "t_am_pm": np.array(AMPM, dtype=object),
    "t_meal_time": np.array(MEALS, dtype=object),
    "sm_type": np.array(SHIP_TYPES, dtype=object),
    "sm_carrier": np.array(CARRIERS, dtype=object),
    "w_state": np.array(STATES, dtype=object),
    "w_country": np.array(COUNTRIES[:1], dtype=object),
    "ca_location_type": np.array(
        ["apartment", "condo", "single family"], dtype=object
    ),
}

BRANDS = np.array(
    [f"brand#{i}" for i in range(1, 1001)], dtype=object
)


def _id_dict(keys, fmt="AAAAAAAA{:08X}"):
    """(codes, dictionary) for a per-row business-key string column."""
    d = np.array([fmt.format(int(k)) for k in keys], dtype=object)
    return np.arange(len(d), dtype=np.int32), d


def _vocab_codes(key: str, idx, vocab_name: str):
    """(codes, dictionary) drawn uniformly from a shared vocabulary."""
    vocab = _VOCABS[vocab_name]
    return (
        (h64(key, idx) % np.uint64(len(vocab))).astype(np.int32), vocab
    )


_GMT_OFFSETS = np.array([-500, -600, -700, -800, -1000])


def _gmt_offset(key: str, idx):
    """Scaled decimal(5,2) US GMT offsets -5..-8 (plus -10 HI)."""
    return _GMT_OFFSETS[(h64(key, idx) % np.uint64(5)).astype(np.int64)]


def _county_codes(key: str, idx):
    return (h64(key, idx) % np.uint64(120)).astype(np.int32), _COUNTIES


def _nullable(key: str, idx, values, frac_pct: int = 4):
    """~frac% NULL foreign keys (dsdgen's nullable FK behavior)."""
    nulls = (h64(key + "$null", idx) % np.uint64(100)).astype(np.int64) < frac_pct
    return values, ~nulls


def generate(
    table: str,
    sf: float,
    split: int = 0,
    num_splits: int = 1,
    columns: Optional[Sequence[str]] = None,
):
    schema = SCHEMAS[table]
    all_cols = [c for c, _ in schema]
    cols = list(columns) if columns is not None else all_cols
    counts = _counts(sf)
    n = counts[table]
    lo = (n * split) // num_splits
    hi = (n * (split + 1)) // num_splits
    idx = np.arange(lo, hi, dtype=np.int64)
    values: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}

    if table == "date_dim":
        days = idx + EPOCH_OFFSET  # days since 1970-01-01
        # derive civil fields host-side (vectorized numpy datetime)
        dates = np.datetime64("1970-01-01") + days
        years = dates.astype("datetime64[Y]").astype(int) + 1970
        months = dates.astype("datetime64[M]").astype(int) % 12 + 1
        doms = (dates - dates.astype("datetime64[M]")).astype(int) + 1
        for c in cols:
            if c == "d_date_sk":
                values[c] = idx + DATE_SK_BASE
            elif c == "d_date":
                values[c] = days.astype(np.int32)
            elif c == "d_year":
                values[c] = years.astype(np.int64)
            elif c == "d_moy":
                values[c] = months.astype(np.int64)
            elif c == "d_dom":
                values[c] = doms.astype(np.int64)
            elif c == "d_qoy":
                values[c] = ((months - 1) // 3 + 1).astype(np.int64)
    elif table == "item":
        for c in cols:
            if c == "i_item_sk":
                values[c] = idx + 1
            elif c == "i_item_id":
                d = np.array(
                    [f"AAAAAAAA{int(k):08d}" for k in idx + 1], dtype=object
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "i_brand_id":
                values[c] = uint_in(c, idx, 1, 1000)
            elif c == "i_brand":
                values[c] = (uint_in("i_brand_id", idx, 1, 1000) - 1).astype(np.int32)
                dicts[c] = BRANDS
            elif c == "i_manufact_id":
                values[c] = uint_in(c, idx, 1, 1000)
            elif c == "i_manager_id":
                values[c] = uint_in(c, idx, 1, 100)
            elif c == "i_category_id":
                values[c] = uint_in(c, idx, 1, 10)
            elif c == "i_category":
                values[c] = (uint_in("i_category_id", idx, 1, 10) - 1).astype(np.int32)
                dicts[c] = _VOCABS["i_category"]
            elif c == "i_class_id":
                values[c] = uint_in(c, idx, 1, 16)
            elif c == "i_class":
                values[c] = (uint_in("i_class_id", idx, 1, 16) - 1).astype(np.int32)
                dicts[c] = _VOCABS["i_class"]
            elif c == "i_current_price":
                values[c] = uint_in(c, idx, 100, 9999)
    elif table == "store":
        for c in cols:
            if c == "s_store_sk":
                values[c] = idx + 1
            elif c == "s_store_id":
                d = np.array([f"S{int(k):08d}" for k in idx + 1], dtype=object)
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "s_store_name":
                d = np.array([f"store {int(k)}" for k in idx + 1], dtype=object)
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "s_number_employees":
                values[c] = uint_in(c, idx, 200, 300)
            elif c == "s_city":
                values[c], dicts[c] = _vocab_codes(c, idx, "ca_city")
            elif c == "s_county":
                values[c], dicts[c] = _county_codes(c, idx)
            elif c == "s_state":
                values[c], dicts[c] = _vocab_codes(c, idx, "ca_state")
            elif c == "s_gmt_offset":
                values[c] = _gmt_offset(c, idx)
    elif table == "promotion":
        for c in cols:
            if c == "p_promo_sk":
                values[c] = idx + 1
            elif c == "p_promo_id":
                d = np.array([f"P{int(k):08d}" for k in idx + 1], dtype=object)
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c in ("p_channel_email", "p_channel_event"):
                values[c] = (h64(c, idx) % np.uint64(2)).astype(np.int32)
                dicts[c] = _VOCABS[c]
    elif table == "customer_demographics":
        # index decomposes into the demographics cross product
        for c in cols:
            if c == "cd_demo_sk":
                values[c] = idx + 1
            elif c == "cd_gender":
                values[c] = (idx % 2).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "cd_marital_status":
                values[c] = ((idx // 2) % 5).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "cd_education_status":
                values[c] = ((idx // 10) % 7).astype(np.int32)
                dicts[c] = _VOCABS[c]
    elif table in ("store_sales", "catalog_sales", "web_sales"):
        _gen_sales(table, idx, cols, counts, values, validity, dicts)
    elif table == "customer":
        for c in cols:
            if c == "c_customer_sk":
                values[c] = idx + 1
            elif c == "c_customer_id":
                values[c], dicts[c] = _id_dict(idx + 1)
            elif c == "c_current_cdemo_sk":
                v = 1 + (
                    h64(c, idx) % np.uint64(counts["customer_demographics"])
                ).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "c_current_hdemo_sk":
                v = 1 + (
                    h64(c, idx) % np.uint64(counts["household_demographics"])
                ).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "c_current_addr_sk":
                values[c] = 1 + (
                    h64(c, idx) % np.uint64(counts["customer_address"])
                ).astype(np.int64)
            elif c == "c_first_name":
                values[c] = (
                    h64(c, idx) % np.uint64(len(FIRST_NAMES))
                ).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "c_last_name":
                values[c] = (
                    h64(c, idx) % np.uint64(len(LAST_NAMES))
                ).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "c_preferred_cust_flag":
                values[c] = (h64(c, idx) % np.uint64(2)).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "c_birth_year":
                values[c] = uint_in(c, idx, 1924, 1992)
            elif c == "c_birth_month":
                values[c] = uint_in(c, idx, 1, 12)
            elif c == "c_birth_country":
                values[c] = (
                    h64(c, idx) % np.uint64(len(COUNTRIES))
                ).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "c_email_address":
                d = np.array(
                    [f"c{int(k)}@example.com" for k in idx + 1], dtype=object
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c in ("c_first_sales_date_sk", "c_first_shipto_date_sk"):
                v = DATE_SK_BASE + _SALES_DATE_LO + (
                    h64(c, idx) % np.uint64(_SALES_NDATES)
                ).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
    elif table == "customer_address":
        for c in cols:
            if c == "ca_address_sk":
                values[c] = idx + 1
            elif c == "ca_address_id":
                values[c], dicts[c] = _id_dict(idx + 1)
            elif c == "ca_street_number":
                d = np.array(
                    [str(int(k)) for k in h64(c, idx) % np.uint64(1000)],
                    dtype=object,
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "ca_city":
                values[c], dicts[c] = _vocab_codes(c, idx, "ca_city")
            elif c == "ca_county":
                values[c], dicts[c] = _county_codes(c, idx)
            elif c == "ca_state":
                values[c], dicts[c] = _vocab_codes(c, idx, "ca_state")
            elif c == "ca_zip":
                d = np.array(
                    [f"{int(k):05d}" for k in h64(c, idx) % np.uint64(100000)],
                    dtype=object,
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "ca_country":
                values[c] = np.zeros(len(idx), dtype=np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "ca_gmt_offset":
                values[c] = _gmt_offset(c, idx)
            elif c == "ca_location_type":
                values[c] = (h64(c, idx) % np.uint64(3)).astype(np.int32)
                dicts[c] = _VOCABS[c]
    elif table == "household_demographics":
        # 7200 = income_band(20) x buy_potential(6) x dep(10) x vehicle(6)
        for c in cols:
            if c == "hd_demo_sk":
                values[c] = idx + 1
            elif c == "hd_income_band_sk":
                values[c] = (idx % 20) + 1
            elif c == "hd_buy_potential":
                values[c] = ((idx // 20) % 6).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "hd_dep_count":
                values[c] = (idx // 120) % 10
            elif c == "hd_vehicle_count":
                values[c] = ((idx // 1200) % 6) - 1
    elif table == "time_dim":
        hours = idx // 3600
        for c in cols:
            if c == "t_time_sk":
                values[c] = idx
            elif c == "t_time_id":
                values[c], dicts[c] = _id_dict(idx)
            elif c == "t_time":
                values[c] = idx
            elif c == "t_hour":
                values[c] = hours
            elif c == "t_minute":
                values[c] = (idx // 60) % 60
            elif c == "t_second":
                values[c] = idx % 60
            elif c == "t_am_pm":
                values[c] = (hours >= 12).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "t_meal_time":
                meal = np.where(
                    (hours >= 6) & (hours < 9), 0,
                    np.where(
                        (hours >= 11) & (hours < 14), 1,
                        np.where((hours >= 17) & (hours < 21), 2, 0),
                    ),
                ).astype(np.int32)
                values[c] = meal
                validity[c] = (
                    ((hours >= 6) & (hours < 9))
                    | ((hours >= 11) & (hours < 14))
                    | ((hours >= 17) & (hours < 21))
                )
                dicts[c] = _VOCABS[c]
    elif table == "warehouse":
        for c in cols:
            if c == "w_warehouse_sk":
                values[c] = idx + 1
            elif c == "w_warehouse_name":
                d = np.array(
                    [f"Warehouse {int(k)}" for k in idx + 1], dtype=object
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "w_warehouse_sq_ft":
                values[c] = uint_in(c, idx, 50_000, 990_000)
            elif c == "w_city":
                values[c], dicts[c] = _vocab_codes(c, idx, "ca_city")
            elif c == "w_state":
                values[c], dicts[c] = _vocab_codes(c, idx, "ca_state")
            elif c == "w_country":
                values[c] = np.zeros(len(idx), dtype=np.int32)
                dicts[c] = _VOCABS[c]
    elif table == "ship_mode":
        for c in cols:
            if c == "sm_ship_mode_sk":
                values[c] = idx + 1
            elif c == "sm_ship_mode_id":
                values[c], dicts[c] = _id_dict(idx + 1)
            elif c == "sm_type":
                values[c] = (idx % 5).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "sm_carrier":
                values[c] = (idx % 20).astype(np.int32)
                dicts[c] = _VOCABS[c]
    elif table == "reason":
        for c in cols:
            if c == "r_reason_sk":
                values[c] = idx + 1
            elif c == "r_reason_id":
                values[c], dicts[c] = _id_dict(idx + 1)
            elif c == "r_reason_desc":
                d = np.array(
                    [f"reason {int(k)}" for k in idx + 1], dtype=object
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
    elif table == "income_band":
        for c in cols:
            if c == "ib_income_band_sk":
                values[c] = idx + 1
            elif c == "ib_lower_bound":
                values[c] = idx * 10_000
            elif c == "ib_upper_bound":
                values[c] = idx * 10_000 + 9_999
    else:
        raise KeyError(table)
    return values, validity, dicts, hi - lo


# dsdgen draws sales dates from [1998-01-02, 2003-01-02]
# (d_date_sk 2450816..2452643) — the window the benchmark queries'
# d_year predicates (1998..2002, e.g. Q7's d_year = 2000) target
_SALES_NDATES = 1827
_SALES_DATE_LO = 2450816 - DATE_SK_BASE
_COUNTIES = np.array(
    [f"{c} County" for c in (
        "Williamson", "Walker", "Ziebach", "Daviess", "Barrow",
        "Fairfield", "Luce", "Richland", "Bronx", "Maverick",
        "Mobile", "Huron", "Kittitas", "Jackson", "Mesa",
    )] + [f"County {i}" for i in range(15, 120)],
    dtype=object,
)

# per-channel column prefixes and line-grouping (several fact rows share
# one ticket/order whose customer/date/store attributes agree — Q68/Q79
# group by ss_ticket_number, Q94-ish count distinct order numbers)
_SALES_SPEC = {
    "store_sales": ("ss", 12, "ss_ticket_number"),
    "catalog_sales": ("cs", 10, "cs_order_number"),
    "web_sales": ("ws", 12, "ws_order_number"),
}


def _gen_sales(table, idx, cols, counts, values, validity, dicts):
    """Shared generator for the three sales channels: per-GROUP (ticket/
    order) foreign keys so grouped queries see realistic co-occurrence,
    per-ROW item/quantity/pricing with consistent arithmetic
    (ext = unit x quantity, profit = paid - wholesale).  Pricing hashes
    are memoized and computed only when a pricing column is requested —
    pruned key-only scans (Q96's count(*)) skip them entirely."""
    pre, per_group, group_col = _SALES_SPEC[table]
    grp = idx // per_group

    def fk(col, base_idx, count, nullable=True):
        v = 1 + (h64(col, base_idx) % np.uint64(count)).astype(np.int64)
        if nullable:
            return _nullable(col, base_idx, v)
        return v, None

    def put(col, v, ok=None):
        values[col] = v
        if ok is not None:
            validity[col] = ok

    _price = {}

    def price(name):
        if name not in _price:
            _price["qty"] = uint_in(f"{pre}_quantity", idx, 1, 100)
            lp = uint_in(f"{pre}_list_price", idx, 100, 20000)
            disc = (
                h64(f"{pre}_sales_price", idx) % np.uint64(100)
            ).astype(np.int64)
            _price["lp"] = lp
            _price["sp"] = (lp * (100 - disc)) // 100
            _price["wc"] = uint_in(f"{pre}_wholesale_cost", idx, 100, 10000)
        return _price[name]

    for c in cols:
        suffix = c[len(pre) + 1:]
        if c == group_col:
            put(c, grp + 1)
        elif suffix == "sold_date_sk":
            v = DATE_SK_BASE + _SALES_DATE_LO + (
                h64(c, grp) % np.uint64(_SALES_NDATES)
            ).astype(np.int64)
            put(c, *_nullable(c, grp, v))
        elif suffix == "ship_date_sk":
            sold = DATE_SK_BASE + _SALES_DATE_LO + (
                h64(f"{pre}_sold_date_sk", grp) % np.uint64(_SALES_NDATES)
            ).astype(np.int64)
            v = sold + 2 + (h64(c, grp) % np.uint64(90)).astype(np.int64)
            put(c, *_nullable(c, grp, v))
        elif suffix == "sold_time_sk":
            put(c, *_nullable(
                c, grp,
                (h64(c, grp) % np.uint64(86_400)).astype(np.int64),
            ))
        elif suffix == "item_sk":
            put(c, *fk(c, idx, counts["item"], nullable=False))
        elif suffix in ("customer_sk", "bill_customer_sk"):
            put(c, *fk(c, grp, counts["customer"]))
        elif suffix in ("cdemo_sk", "bill_cdemo_sk"):
            put(c, *fk(c, grp, counts["customer_demographics"]))
        elif suffix in ("hdemo_sk", "bill_hdemo_sk"):
            put(c, *fk(c, grp, counts["household_demographics"]))
        elif suffix in ("addr_sk", "bill_addr_sk"):
            put(c, *fk(c, grp, counts["customer_address"]))
        elif suffix == "store_sk":
            put(c, *fk(c, grp, counts["store"]))
        elif suffix == "warehouse_sk":
            put(c, *fk(c, grp, counts["warehouse"]))
        elif suffix == "ship_mode_sk":
            put(c, *fk(c, grp, counts["ship_mode"]))
        elif suffix == "web_page_sk":
            put(c, *fk(c, grp, 60))
        elif suffix == "promo_sk":
            put(c, *fk(c, idx, counts["promotion"]))
        elif suffix == "quantity":
            put(c, price("qty"))
        elif suffix == "wholesale_cost":
            put(c, price("wc"))
        elif suffix == "list_price":
            put(c, price("lp"))
        elif suffix == "sales_price":
            put(c, price("sp"))
        elif suffix == "ext_sales_price":
            put(c, price("sp") * price("qty"))
        elif suffix == "ext_list_price":
            put(c, price("lp") * price("qty"))
        elif suffix == "ext_wholesale_cost":
            put(c, price("wc") * price("qty"))
        elif suffix == "ext_discount_amt":
            put(c, (price("lp") - price("sp")) * price("qty"))
        elif suffix == "coupon_amt":
            put(c, np.where(
                (h64(c, idx) % np.uint64(10)).astype(np.int64) == 0,
                uint_in(c, idx, 100, 50000),
                0,
            ))
        elif suffix == "net_paid":
            put(c, price("sp") * price("qty"))
        elif suffix == "net_profit":
            put(c, (price("sp") - price("wc")) * price("qty"))
        else:
            raise KeyError(c)


# --- SPI ---------------------------------------------------------------


class TpcdsMetadata(ConnectorMetadata):
    def __init__(self, sf: float):
        self.sf = sf

    def list_tables(self):
        return list(SCHEMAS)

    def get_table_schema(self, table):
        return TableSchema(
            table, tuple(ColumnSchema(c, t) for c, t in SCHEMAS[table])
        )

    def get_table_statistics(self, table):
        counts = _counts(self.sf)
        n = counts[table]
        pk = {
            "date_dim": "d_date_sk", "item": "i_item_sk",
            "store": "s_store_sk", "promotion": "p_promo_sk",
            "customer_demographics": "cd_demo_sk",
            "customer": "c_customer_sk",
            "customer_address": "ca_address_sk",
            "household_demographics": "hd_demo_sk",
            "time_dim": "t_time_sk", "warehouse": "w_warehouse_sk",
            "ship_mode": "sm_ship_mode_sk", "reason": "r_reason_sk",
            "income_band": "ib_income_band_sk",
        }.get(table)
        # NDVs of the generator's bounded-domain columns (TpchMetadata-style
        # statistics): missing ndv makes the CBO assume ndv = row_count,
        # which balloons group-by capacities to the scan size
        ndv = {
            "d_year": 201, "d_moy": 12, "d_dom": 31, "d_qoy": 4,
            "i_brand_id": 1000, "i_brand": len(BRANDS),
            "i_manufact_id": 1000, "i_manager_id": 100,
            "i_category_id": 10, "i_category": 10,
            "i_class_id": 16, "i_class": 16, "i_current_price": 9900,
            "cd_gender": 2, "cd_marital_status": 5,
            "cd_education_status": 7,
            "p_channel_email": 2, "p_channel_event": 2,
            "s_store_name": counts["store"],
            "s_store_id": counts["store"],
            "i_item_id": counts["item"],
            "t_hour": 24, "t_minute": 60, "t_second": 60,
            "t_am_pm": 2, "t_meal_time": 3,
            "hd_income_band_sk": 20, "hd_buy_potential": 6,
            "hd_dep_count": 10, "hd_vehicle_count": 6,
            "ca_city": len(CITIES), "ca_state": len(STATES),
            "ca_county": len(_COUNTIES), "ca_country": 1,
            "ca_gmt_offset": 5,
            "c_first_name": len(FIRST_NAMES),
            "c_last_name": len(LAST_NAMES),
            "c_birth_year": 69, "c_birth_month": 12,
            "c_birth_country": len(COUNTRIES),
            "sm_type": 5, "sm_carrier": 20,
            "w_state": len(STATES),
        }
        # the three sales channels share FK-domain NDVs by suffix
        for pre, grp_col, grp_div in (
            ("ss", "ss_ticket_number", 12),
            ("cs", "cs_order_number", 10),
            ("ws", "ws_order_number", 12),
        ):
            fact = {"ss": "store_sales", "cs": "catalog_sales",
                    "ws": "web_sales"}[pre]
            groups = max(1, counts[fact] // grp_div)
            ndv.update({
                f"{pre}_quantity": 100,
                f"{pre}_item_sk": counts["item"],
                f"{pre}_promo_sk": counts["promotion"],
                f"{pre}_store_sk": counts["store"],
                f"{pre}_warehouse_sk": counts["warehouse"],
                f"{pre}_ship_mode_sk": counts["ship_mode"],
                f"{pre}_web_page_sk": 60,
                f"{pre}_customer_sk": min(counts["customer"], groups),
                f"{pre}_bill_customer_sk": min(counts["customer"], groups),
                f"{pre}_cdemo_sk": min(
                    counts["customer_demographics"], groups),
                f"{pre}_bill_cdemo_sk": min(
                    counts["customer_demographics"], groups),
                f"{pre}_hdemo_sk": min(
                    counts["household_demographics"], groups),
                f"{pre}_bill_hdemo_sk": min(
                    counts["household_demographics"], groups),
                f"{pre}_addr_sk": min(counts["customer_address"], groups),
                f"{pre}_bill_addr_sk": min(
                    counts["customer_address"], groups),
                f"{pre}_sold_date_sk": _SALES_NDATES,
                f"{pre}_ship_date_sk": _SALES_NDATES + 91,
                f"{pre}_sold_time_sk": 86_400,
                grp_col: groups,
            })
        # value ranges for selectivity estimation (date windows, years)
        rng = {
            "d_year": (1900.0, 2100.0), "d_moy": (1.0, 12.0),
            "d_dom": (1.0, 31.0), "d_qoy": (1.0, 4.0),
            "d_date_sk": (float(DATE_SK_BASE),
                          float(DATE_SK_BASE + DATE_DIM_ROWS - 1)),
            "t_hour": (0.0, 23.0), "t_minute": (0.0, 59.0),
            "c_birth_year": (1924.0, 1992.0),
            "hd_dep_count": (0.0, 9.0), "hd_vehicle_count": (-1.0, 4.0),
            "i_manufact_id": (1.0, 1000.0), "i_manager_id": (1.0, 100.0),
            "i_brand_id": (1.0, 1000.0), "i_category_id": (1.0, 10.0),
        }
        for pre in ("ss", "cs", "ws"):
            lo = float(DATE_SK_BASE + _SALES_DATE_LO)
            rng[f"{pre}_sold_date_sk"] = (lo, lo + _SALES_NDATES - 1)
            rng[f"{pre}_quantity"] = (1.0, 100.0)
        cols = {}
        for c, t in SCHEMAS[table]:
            lohi = rng.get(c, (None, None))
            if c == pk:
                cols[c] = ColumnStatistics(
                    distinct_count=float(n),
                    min_value=lohi[0], max_value=lohi[1],
                )
            elif c in ndv or lohi[0] is not None:
                cols[c] = ColumnStatistics(
                    distinct_count=(
                        float(min(ndv[c], n)) if c in ndv else None
                    ),
                    min_value=lohi[0], max_value=lohi[1],
                )
        return TableStatistics(float(n), cols)


class TpcdsSplitManager(SplitManager):
    def __init__(self, sf):
        self.sf = sf

    def get_splits(self, table, desired, constraint=None):
        n = _counts(self.sf)[table]
        k = max(1, min(desired, (n + 65535) // 65536))
        return [Split(table, i, k) for i in range(k)]


class TpcdsPageSource(PageSource):
    def __init__(self, sf, split, columns):
        self.sf = sf
        self.split = split
        self.columns = list(columns)
        self._dicts: Dict[str, np.ndarray] = {}

    def pages(self):
        from ..page import Column, Page

        values, validity, dicts, count = generate(
            self.split.table, self.sf, self.split.ordinal, self.split.total,
            self.columns,
        )
        self._dicts = dicts
        types = dict(SCHEMAS[self.split.table])
        cols = [
            Column(types[c], values[c], validity.get(c), dicts.get(c))
            for c in self.columns
        ]
        yield Page(cols, count, self.columns)

    def dictionaries(self):
        out = dict(self._dicts)
        types = dict(SCHEMAS[self.split.table])
        for c in self.columns:
            if types[c].is_dictionary and c in _VOCABS and c not in out:
                out[c] = _VOCABS[c]
        return out


class TpcdsPageSourceProvider(PageSourceProvider):
    def __init__(self, sf):
        self.sf = sf

    def create_page_source(self, split, columns):
        return TpcdsPageSource(self.sf, split, columns)


class TpcdsConnector(Connector):
    def __init__(self, name: str, sf: float):
        self.name = name
        self.sf = sf

    def metadata(self):
        return TpcdsMetadata(self.sf)

    def split_manager(self):
        return TpcdsSplitManager(self.sf)

    def page_source_provider(self):
        return TpcdsPageSourceProvider(self.sf)


class TpcdsConnectorFactory(ConnectorFactory):
    name = "tpcds"

    def create(self, catalog_name: str, config: dict) -> TpcdsConnector:
        sf = float(config.get("tpcds.scale-factor", 0.01))
        return TpcdsConnector(catalog_name, sf)
