"""TPC-DS generator connector (subset).

Reference parity: plugin/trino-tpcds (TpcdsConnectorFactory, TpcdsMetadata,
TpcdsSplitManager/TpcdsRecordSetProvider over io.trino.tpcds dsdgen).

Same counter-based (splitmix64) design as the tpch connector: every
attribute is a pure function of (table, column, row index), vectorized in
numpy; splits generate independently.  Covers the star-schema tables used
by the driver benchmark configs (TPC-DS Q3/Q7) and common derived queries:
store_sales + date_dim, item, customer_demographics, promotion, store.

Unlike TPC-H, TPC-DS fact-table foreign keys are nullable (~4%), which
exercises the engine's null-key join semantics.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)
from .tpch import h64, mix64, uint_in

DEC = T.decimal(7, 2)

GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = [
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
]
CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry", "Men",
    "Music", "Shoes", "Sports", "Women",
]
CLASSES = [f"class#{i}" for i in range(1, 17)]
YN = ["N", "Y"]

DATE_DIM_ROWS = 73049  # 1900-01-02 .. 2100-01-01 (dsdgen fixed)
DATE_SK_BASE = 2415022  # julian day of 1900-01-02
EPOCH_OFFSET = -25567  # days from 1970-01-01 back to 1900-01-02


def _counts(sf: float) -> Dict[str, int]:
    return {
        "date_dim": DATE_DIM_ROWS,
        "item": max(10, int(18_000 * max(1.0, sf) ** 0.5)),
        "store": max(2, int(12 * max(1.0, sf) ** 0.5)),
        "promotion": max(5, int(300 * max(1.0, sf) ** 0.5)),
        "customer_demographics": 1_920_800 if sf >= 0.1 else 19_208,
        "store_sales": max(10, int(2_880_404 * sf)),
    }


SCHEMAS: Dict[str, List[Tuple[str, T.Type]]] = {
    "date_dim": [
        ("d_date_sk", T.BIGINT),
        ("d_date", T.DATE),
        ("d_year", T.BIGINT),
        ("d_moy", T.BIGINT),
        ("d_dom", T.BIGINT),
        ("d_qoy", T.BIGINT),
    ],
    "item": [
        ("i_item_sk", T.BIGINT),
        ("i_item_id", T.VARCHAR),
        ("i_brand_id", T.BIGINT),
        ("i_brand", T.VARCHAR),
        ("i_manufact_id", T.BIGINT),
        ("i_manager_id", T.BIGINT),
        ("i_category_id", T.BIGINT),
        ("i_category", T.VARCHAR),
        ("i_class_id", T.BIGINT),
        ("i_class", T.VARCHAR),
        ("i_current_price", DEC),
    ],
    "store": [
        ("s_store_sk", T.BIGINT),
        ("s_store_id", T.VARCHAR),
        ("s_store_name", T.VARCHAR),
    ],
    "promotion": [
        ("p_promo_sk", T.BIGINT),
        ("p_promo_id", T.VARCHAR),
        ("p_channel_email", T.VARCHAR),
        ("p_channel_event", T.VARCHAR),
    ],
    "customer_demographics": [
        ("cd_demo_sk", T.BIGINT),
        ("cd_gender", T.VARCHAR),
        ("cd_marital_status", T.VARCHAR),
        ("cd_education_status", T.VARCHAR),
    ],
    "store_sales": [
        ("ss_sold_date_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT),
        ("ss_customer_sk", T.BIGINT),
        ("ss_cdemo_sk", T.BIGINT),
        ("ss_store_sk", T.BIGINT),
        ("ss_promo_sk", T.BIGINT),
        ("ss_quantity", T.BIGINT),
        ("ss_list_price", DEC),
        ("ss_sales_price", DEC),
        ("ss_ext_sales_price", DEC),
        ("ss_ext_discount_amt", DEC),
        ("ss_coupon_amt", DEC),
        ("ss_net_profit", DEC),
    ],
}

_VOCABS = {
    "cd_gender": np.array(GENDERS, dtype=object),
    "cd_marital_status": np.array(MARITAL, dtype=object),
    "cd_education_status": np.array(EDUCATION, dtype=object),
    "i_category": np.array(CATEGORIES, dtype=object),
    "i_class": np.array(CLASSES, dtype=object),
    "p_channel_email": np.array(YN, dtype=object),
    "p_channel_event": np.array(YN, dtype=object),
}

BRANDS = np.array(
    [f"brand#{i}" for i in range(1, 1001)], dtype=object
)


def _nullable(key: str, idx, values, frac_pct: int = 4):
    """~frac% NULL foreign keys (dsdgen's nullable FK behavior)."""
    nulls = (h64(key + "$null", idx) % np.uint64(100)).astype(np.int64) < frac_pct
    return values, ~nulls


def generate(
    table: str,
    sf: float,
    split: int = 0,
    num_splits: int = 1,
    columns: Optional[Sequence[str]] = None,
):
    schema = SCHEMAS[table]
    all_cols = [c for c, _ in schema]
    cols = list(columns) if columns is not None else all_cols
    counts = _counts(sf)
    n = counts[table]
    lo = (n * split) // num_splits
    hi = (n * (split + 1)) // num_splits
    idx = np.arange(lo, hi, dtype=np.int64)
    values: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}

    if table == "date_dim":
        days = idx + EPOCH_OFFSET  # days since 1970-01-01
        # derive civil fields host-side (vectorized numpy datetime)
        dates = np.datetime64("1970-01-01") + days
        years = dates.astype("datetime64[Y]").astype(int) + 1970
        months = dates.astype("datetime64[M]").astype(int) % 12 + 1
        doms = (dates - dates.astype("datetime64[M]")).astype(int) + 1
        for c in cols:
            if c == "d_date_sk":
                values[c] = idx + DATE_SK_BASE
            elif c == "d_date":
                values[c] = days.astype(np.int32)
            elif c == "d_year":
                values[c] = years.astype(np.int64)
            elif c == "d_moy":
                values[c] = months.astype(np.int64)
            elif c == "d_dom":
                values[c] = doms.astype(np.int64)
            elif c == "d_qoy":
                values[c] = ((months - 1) // 3 + 1).astype(np.int64)
    elif table == "item":
        for c in cols:
            if c == "i_item_sk":
                values[c] = idx + 1
            elif c == "i_item_id":
                d = np.array(
                    [f"AAAAAAAA{int(k):08d}" for k in idx + 1], dtype=object
                )
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "i_brand_id":
                values[c] = uint_in(c, idx, 1, 1000)
            elif c == "i_brand":
                values[c] = (uint_in("i_brand_id", idx, 1, 1000) - 1).astype(np.int32)
                dicts[c] = BRANDS
            elif c == "i_manufact_id":
                values[c] = uint_in(c, idx, 1, 1000)
            elif c == "i_manager_id":
                values[c] = uint_in(c, idx, 1, 100)
            elif c == "i_category_id":
                values[c] = uint_in(c, idx, 1, 10)
            elif c == "i_category":
                values[c] = (uint_in("i_category_id", idx, 1, 10) - 1).astype(np.int32)
                dicts[c] = _VOCABS["i_category"]
            elif c == "i_class_id":
                values[c] = uint_in(c, idx, 1, 16)
            elif c == "i_class":
                values[c] = (uint_in("i_class_id", idx, 1, 16) - 1).astype(np.int32)
                dicts[c] = _VOCABS["i_class"]
            elif c == "i_current_price":
                values[c] = uint_in(c, idx, 100, 9999)
    elif table == "store":
        for c in cols:
            if c == "s_store_sk":
                values[c] = idx + 1
            elif c == "s_store_id":
                d = np.array([f"S{int(k):08d}" for k in idx + 1], dtype=object)
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c == "s_store_name":
                d = np.array([f"store {int(k)}" for k in idx + 1], dtype=object)
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
    elif table == "promotion":
        for c in cols:
            if c == "p_promo_sk":
                values[c] = idx + 1
            elif c == "p_promo_id":
                d = np.array([f"P{int(k):08d}" for k in idx + 1], dtype=object)
                values[c] = np.arange(len(d), dtype=np.int32)
                dicts[c] = d
            elif c in ("p_channel_email", "p_channel_event"):
                values[c] = (h64(c, idx) % np.uint64(2)).astype(np.int32)
                dicts[c] = _VOCABS[c]
    elif table == "customer_demographics":
        # index decomposes into the demographics cross product
        for c in cols:
            if c == "cd_demo_sk":
                values[c] = idx + 1
            elif c == "cd_gender":
                values[c] = (idx % 2).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "cd_marital_status":
                values[c] = ((idx // 2) % 5).astype(np.int32)
                dicts[c] = _VOCABS[c]
            elif c == "cd_education_status":
                values[c] = ((idx // 10) % 7).astype(np.int32)
                dicts[c] = _VOCABS[c]
    elif table == "store_sales":
        ndates = 1827  # 5-year sales window within date_dim
        # dsdgen draws store_sales dates from [1998-01-02, 2003-01-02]
        # (d_date_sk 2450816..2452643) — the window the benchmark queries'
        # d_year predicates (1998..2002, e.g. Q7's d_year = 2000) target
        date_lo = 2450816 - DATE_SK_BASE
        for c in cols:
            if c == "ss_sold_date_sk":
                v = DATE_SK_BASE + date_lo + (
                    h64(c, idx) % np.uint64(ndates)
                ).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "ss_item_sk":
                values[c] = 1 + (h64(c, idx) % np.uint64(counts["item"])).astype(np.int64)
            elif c == "ss_customer_sk":
                v = 1 + (h64(c, idx) % np.uint64(100000)).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "ss_cdemo_sk":
                v = 1 + (
                    h64(c, idx) % np.uint64(counts["customer_demographics"])
                ).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "ss_store_sk":
                v = 1 + (h64(c, idx) % np.uint64(counts["store"])).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "ss_promo_sk":
                v = 1 + (h64(c, idx) % np.uint64(counts["promotion"])).astype(np.int64)
                values[c], validity[c] = _nullable(c, idx, v)
            elif c == "ss_quantity":
                values[c] = uint_in(c, idx, 1, 100)
            elif c == "ss_list_price":
                values[c] = uint_in(c, idx, 100, 20000)
            elif c == "ss_sales_price":
                lp = uint_in("ss_list_price", idx, 100, 20000)
                disc = h64(c, idx) % np.uint64(100)
                values[c] = (lp * (100 - disc.astype(np.int64))) // 100
            elif c == "ss_ext_sales_price":
                lp = uint_in("ss_list_price", idx, 100, 20000)
                disc = h64("ss_sales_price", idx) % np.uint64(100)
                sp = (lp * (100 - disc.astype(np.int64))) // 100
                qty = uint_in("ss_quantity", idx, 1, 100)
                values[c] = sp * qty
            elif c == "ss_ext_discount_amt":
                values[c] = uint_in(c, idx, 0, 100000)
            elif c == "ss_coupon_amt":
                values[c] = np.where(
                    (h64(c, idx) % np.uint64(10)).astype(np.int64) == 0,
                    uint_in(c, idx, 100, 50000),
                    0,
                )
            elif c == "ss_net_profit":
                values[c] = uint_in(c, idx, -10000, 50000)
    else:
        raise KeyError(table)
    return values, validity, dicts, hi - lo


# --- SPI ---------------------------------------------------------------


class TpcdsMetadata(ConnectorMetadata):
    def __init__(self, sf: float):
        self.sf = sf

    def list_tables(self):
        return list(SCHEMAS)

    def get_table_schema(self, table):
        return TableSchema(
            table, tuple(ColumnSchema(c, t) for c, t in SCHEMAS[table])
        )

    def get_table_statistics(self, table):
        counts = _counts(self.sf)
        n = counts[table]
        pk = {
            "date_dim": "d_date_sk", "item": "i_item_sk",
            "store": "s_store_sk", "promotion": "p_promo_sk",
            "customer_demographics": "cd_demo_sk",
        }.get(table)
        # NDVs of the generator's bounded-domain columns (TpchMetadata-style
        # statistics): missing ndv makes the CBO assume ndv = row_count,
        # which balloons group-by capacities to the scan size
        ndv = {
            "d_year": 201, "d_moy": 12, "d_dom": 31, "d_qoy": 4,
            "i_brand_id": 1000, "i_brand": len(BRANDS),
            "i_manufact_id": 1000, "i_manager_id": 100,
            "i_category_id": 10, "i_category": 10,
            "i_class_id": 16, "i_class": 16, "i_current_price": 9900,
            "cd_gender": 2, "cd_marital_status": 5,
            "cd_education_status": 7,
            "p_channel_email": 2, "p_channel_event": 2,
            "ss_quantity": 100, "ss_store_sk": counts["store"],
            "ss_item_sk": counts["item"],
            "ss_promo_sk": counts["promotion"],
            "ss_cdemo_sk": counts["customer_demographics"],
            "s_store_name": counts["store"],
            "s_store_id": counts["store"],
            "i_item_id": counts["item"],
        }
        cols = {}
        for c, t in SCHEMAS[table]:
            if c == pk:
                cols[c] = ColumnStatistics(distinct_count=float(n))
            elif c in ndv:
                cols[c] = ColumnStatistics(
                    distinct_count=float(min(ndv[c], n))
                )
        return TableStatistics(float(n), cols)


class TpcdsSplitManager(SplitManager):
    def __init__(self, sf):
        self.sf = sf

    def get_splits(self, table, desired, constraint=None):
        n = _counts(self.sf)[table]
        k = max(1, min(desired, (n + 65535) // 65536))
        return [Split(table, i, k) for i in range(k)]


class TpcdsPageSource(PageSource):
    def __init__(self, sf, split, columns):
        self.sf = sf
        self.split = split
        self.columns = list(columns)
        self._dicts: Dict[str, np.ndarray] = {}

    def pages(self):
        from ..page import Column, Page

        values, validity, dicts, count = generate(
            self.split.table, self.sf, self.split.ordinal, self.split.total,
            self.columns,
        )
        self._dicts = dicts
        types = dict(SCHEMAS[self.split.table])
        cols = [
            Column(types[c], values[c], validity.get(c), dicts.get(c))
            for c in self.columns
        ]
        yield Page(cols, count, self.columns)

    def dictionaries(self):
        out = dict(self._dicts)
        types = dict(SCHEMAS[self.split.table])
        for c in self.columns:
            if types[c].is_dictionary and c in _VOCABS and c not in out:
                out[c] = _VOCABS[c]
        return out


class TpcdsPageSourceProvider(PageSourceProvider):
    def __init__(self, sf):
        self.sf = sf

    def create_page_source(self, split, columns):
        return TpcdsPageSource(self.sf, split, columns)


class TpcdsConnector(Connector):
    def __init__(self, name: str, sf: float):
        self.name = name
        self.sf = sf

    def metadata(self):
        return TpcdsMetadata(self.sf)

    def split_manager(self):
        return TpcdsSplitManager(self.sf)

    def page_source_provider(self):
        return TpcdsPageSourceProvider(self.sf)


class TpcdsConnectorFactory(ConnectorFactory):
    name = "tpcds"

    def create(self, catalog_name: str, config: dict) -> TpcdsConnector:
        sf = float(config.get("tpcds.scale-factor", 0.01))
        return TpcdsConnector(catalog_name, sf)
