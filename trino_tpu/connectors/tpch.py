"""TPC-H generator connector — deterministic, split-parallel, column-pruned.

Reference parity: plugin/trino-tpch (TpchConnectorFactory, TpchMetadata with
statistics, TpchSplitManager.java:40 nodes*splitsPerNode splits,
TpchRecordSetProvider/TpchPageSourceProvider streaming generated rows).

TPU-first redesign: instead of the reference's sequential per-row dbgen port,
every attribute is a pure function of (table, column, row-index) via
counter-based hashing (splitmix64 finalizer), fully vectorized in numpy.
Any split of any table therefore generates independently — the property the
reference gets from dbgen's per-split RNG seeking, but without sequential
state, so a TPU host can generate splits in parallel at HBM-feed rate.

dbgen invariants preserved (needed for realistic join fan-outs and the spec
queries' selectivities):
  - sparse orderkeys: 8 used of every 32       (reference OrderGenerator)
  - customers with custkey % 3 == 0 never buy  (CustomerGenerator)
  - p_retailprice is a formula of partkey       (PartGenerator)
  - l_extendedprice = quantity * retailprice(partkey)
  - lineitem (partkey,suppkey) always one of the part's 4 partsupp rows
    (selectToOrderSupplier formula)
  - returnflag/linestatus split around CURRENT_DATE = 1995-06-17
  - 1..7 lineitems per order, dates chained off o_orderdate

Low-cardinality strings are dictionary-encoded against fixed vocabularies;
high-cardinality strings (names, phones, comments) are generated only when
the query requests them (column pruning down the generator — the analog of
TpchPageSourceProvider's projected columns).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Column, Page
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)

M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _fnv(s: str) -> np.uint64:
    h = np.uint64(0xCBF29CE484222325)
    for ch in s.encode():
        h = np.uint64((int(h) ^ ch) * 0x100000001B3 & 0xFFFFFFFFFFFFFFFF)
    return h


def mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — the counter-based RNG core."""
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & M64
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9) & M64
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB) & M64
    return x ^ (x >> np.uint64(31))


def h64(key: str, idx: np.ndarray, salt: int = 0) -> np.ndarray:
    """Deterministic uint64 per (key, index, salt)."""
    base = _fnv(key) ^ np.uint64(salt * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF)
    return mix64(idx.astype(np.uint64) ^ base)


def uint_in(key: str, idx: np.ndarray, lo: int, hi: int, salt: int = 0) -> np.ndarray:
    """Uniform integer in [lo, hi] (inclusive)."""
    span = np.uint64(hi - lo + 1)
    return (h64(key, idx, salt) % span).astype(np.int64) + lo


# --- calendar ----------------------------------------------------------

EPOCH_1992 = 8035  # 1992-01-01 in days since 1970-01-01
ORDER_DATE_SPAN = 2406 - 151  # orderdate in [1992-01-01, 1998-08-02]
CURRENT_DATE = 9298  # 1995-06-17 (dbgen's CURRENTDATE)

# --- vocabularies (reference: io.trino.tpch.Distributions) -------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
RETURN_FLAGS = ["A", "N", "R"]
LINE_STATUS = ["F", "O"]
ORDER_STATUS = ["F", "O", "P"]
MFGRS = [f"Manufacturer#{i}" for i in range(1, 6)]
BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
P_TYPES = [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2 for c in TYPE_S3]
CONT_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONT_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
CONTAINERS = [f"{a} {b}" for a in CONT_S1 for b in CONT_S2]

_COMMENT_WORDS = (
    "blithely bold carefully final regular ironic express silent pending "
    "furiously slyly quickly deposits accounts requests packages theodolites "
    "instructions foxes dependencies pinto beans asymptotes sauternes courts "
    "ideas platelets sleep nag haggle wake above according active against "
    "along among special excuses unusual customer complaints".split()
)


def _comment_vocab(n: int = 2048) -> np.ndarray:
    """Deterministic pool of comment phrases; includes the LIKE-targets of
    Q13 ('special ... requests') and Q16 ('Customer Complaints')."""
    rng = np.random.default_rng(0x7C4)
    out = []
    for i in range(n):
        k = 4 + int(rng.integers(0, 5))
        words = [
            _COMMENT_WORDS[int(rng.integers(0, len(_COMMENT_WORDS)))]
            for _ in range(k)
        ]
        out.append(" ".join(words))
    # guarantee the phrases probed by spec queries appear with ~1% weight
    for j in range(0, n, 97):
        out[j] = "special packages wake furiously requests"
    for j in range(53, n, 211):
        out[j] = "slyly bold Customer Complaints nag"
    return np.array(out, dtype=object)


COMMENTS = _comment_vocab()

# dbgen P_NAME color vocabulary (TPC-H spec 4.2.3 / dists.dss "colors"):
# p_name is 5 words drawn from this list, so predicates like
# p_name LIKE '%green%' (Q9) and LIKE 'forest%' (Q20) select realistic
# fractions instead of matching nothing
P_NAME_WORDS = (
    "almond antique aquamarine azure beige bisque black blanched blue "
    "blush brown burlywood burnished chartreuse chiffon chocolate coral "
    "cornflower cornsilk cream cyan dark deep dim dodger drab firebrick "
    "floral forest frosted gainsboro ghost goldenrod green grey honeydew "
    "hot indian ivory khaki lace lavender lawn lemon light lime linen "
    "magenta maroon medium metallic midnight mint misty moccasin navajo "
    "navy olive orange orchid pale papaya peach peru pink plum powder "
    "puff purple red rose rosy royal saddle salmon sandy seashell sienna "
    "sky slate smoke snow spring steel tan thistle tomato turquoise "
    "violet wheat white yellow"
).split()

DEC = T.decimal(12, 2)

SCHEMAS: Dict[str, List[Tuple[str, T.Type]]] = {
    "region": [
        ("r_regionkey", T.BIGINT),
        ("r_name", T.VARCHAR),
        ("r_comment", T.VARCHAR),
    ],
    "nation": [
        ("n_nationkey", T.BIGINT),
        ("n_name", T.VARCHAR),
        ("n_regionkey", T.BIGINT),
        ("n_comment", T.VARCHAR),
    ],
    "supplier": [
        ("s_suppkey", T.BIGINT),
        ("s_name", T.VARCHAR),
        ("s_address", T.VARCHAR),
        ("s_nationkey", T.BIGINT),
        ("s_phone", T.VARCHAR),
        ("s_acctbal", DEC),
        ("s_comment", T.VARCHAR),
    ],
    "customer": [
        ("c_custkey", T.BIGINT),
        ("c_name", T.VARCHAR),
        ("c_address", T.VARCHAR),
        ("c_nationkey", T.BIGINT),
        ("c_phone", T.VARCHAR),
        ("c_acctbal", DEC),
        ("c_mktsegment", T.VARCHAR),
        ("c_comment", T.VARCHAR),
    ],
    "part": [
        ("p_partkey", T.BIGINT),
        ("p_name", T.VARCHAR),
        ("p_mfgr", T.VARCHAR),
        ("p_brand", T.VARCHAR),
        ("p_type", T.VARCHAR),
        ("p_size", T.BIGINT),
        ("p_container", T.VARCHAR),
        ("p_retailprice", DEC),
        ("p_comment", T.VARCHAR),
    ],
    "partsupp": [
        ("ps_partkey", T.BIGINT),
        ("ps_suppkey", T.BIGINT),
        ("ps_availqty", T.BIGINT),
        ("ps_supplycost", DEC),
        ("ps_comment", T.VARCHAR),
    ],
    "orders": [
        ("o_orderkey", T.BIGINT),
        ("o_custkey", T.BIGINT),
        ("o_orderstatus", T.VARCHAR),
        ("o_totalprice", DEC),
        ("o_orderdate", T.DATE),
        ("o_orderpriority", T.VARCHAR),
        ("o_clerk", T.VARCHAR),
        ("o_shippriority", T.BIGINT),
        ("o_comment", T.VARCHAR),
    ],
    "lineitem": [
        ("l_orderkey", T.BIGINT),
        ("l_partkey", T.BIGINT),
        ("l_suppkey", T.BIGINT),
        ("l_linenumber", T.BIGINT),
        ("l_quantity", DEC),
        ("l_extendedprice", DEC),
        ("l_discount", DEC),
        ("l_tax", DEC),
        ("l_returnflag", T.VARCHAR),
        ("l_linestatus", T.VARCHAR),
        ("l_shipdate", T.DATE),
        ("l_commitdate", T.DATE),
        ("l_receiptdate", T.DATE),
        ("l_shipinstruct", T.VARCHAR),
        ("l_shipmode", T.VARCHAR),
        ("l_comment", T.VARCHAR),
    ],
}

# column name -> fixed vocabulary (shared dictionaries)
_VOCABS: Dict[str, np.ndarray] = {
    "r_name": np.array(REGIONS, dtype=object),
    "n_name": np.array([n for n, _ in NATIONS], dtype=object),
    "c_mktsegment": np.array(SEGMENTS, dtype=object),
    "o_orderpriority": np.array(PRIORITIES, dtype=object),
    "o_orderstatus": np.array(ORDER_STATUS, dtype=object),
    "l_shipinstruct": np.array(INSTRUCTIONS, dtype=object),
    "l_shipmode": np.array(MODES, dtype=object),
    "l_returnflag": np.array(RETURN_FLAGS, dtype=object),
    "l_linestatus": np.array(LINE_STATUS, dtype=object),
    "p_mfgr": np.array(MFGRS, dtype=object),
    "p_brand": np.array(BRANDS, dtype=object),
    "p_type": np.array(P_TYPES, dtype=object),
    "p_container": np.array(CONTAINERS, dtype=object),
    "r_comment": COMMENTS,
    "n_comment": COMMENTS,
    "s_comment": COMMENTS,
    "c_comment": COMMENTS,
    "p_comment": COMMENTS,
    "ps_comment": COMMENTS,
    "o_comment": COMMENTS,
    "l_comment": COMMENTS,
}


def _counts(sf: float) -> Dict[str, int]:
    return {
        "region": 5,
        "nation": 25,
        "supplier": max(1, int(10_000 * sf)),
        "customer": max(1, int(150_000 * sf)),
        "part": max(1, int(200_000 * sf)),
        "partsupp": 4 * max(1, int(200_000 * sf)),
        "orders": max(1, int(1_500_000 * sf)),
        # lineitem count is data-dependent (1..7 per order, avg 4)
        "lineitem": 4 * max(1, int(1_500_000 * sf)),
    }


def _orderkey(j: np.ndarray) -> np.ndarray:
    """Sparse order keys: 8 used out of every 32 (OrderGenerator.makeOrderKey)."""
    return (j // 8) * 32 + (j % 8) + 1


def _custkey_for_order(j: np.ndarray, ncust: int) -> np.ndarray:
    """Uniform over custkeys with key % 3 != 0 (dbgen skips every third)."""
    usable = ncust - ncust // 3
    i = (h64("o_custkey", j) % np.uint64(max(1, usable))).astype(np.int64)
    return 3 * (i // 2) + 1 + (i % 2)


def _retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    return 90000 + (partkey // 10) % 20001 + 100 * (partkey % 1000)


def _ps_suppkey(partkey: np.ndarray, i, nsupp: int) -> np.ndarray:
    """The i-th (0..3) supplier of a part (PartSupplierGenerator formula)."""
    return (partkey + i * (nsupp // 4 + (partkey - 1) // nsupp)) % nsupp + 1


def _line_count(j: np.ndarray) -> np.ndarray:
    return 1 + (h64("l_count", j) % np.uint64(7)).astype(np.int64)


class _Gen:
    """Vectorized column generators for one (table, row-index-range)."""

    def __init__(self, sf: float):
        self.sf = sf
        self.n = _counts(sf)

    # -- small dimension tables ------------------------------------
    def region(self, idx, cols):
        out = {}
        for c in cols:
            if c == "r_regionkey":
                out[c] = idx.astype(np.int64)
            elif c == "r_name":
                out[c] = idx.astype(np.int32)
            elif c == "r_comment":
                out[c] = (h64(c, idx) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out

    def nation(self, idx, cols):
        region_of = np.array([r for _, r in NATIONS], dtype=np.int64)
        out = {}
        for c in cols:
            if c == "n_nationkey":
                out[c] = idx.astype(np.int64)
            elif c == "n_name":
                out[c] = idx.astype(np.int32)
            elif c == "n_regionkey":
                out[c] = region_of[idx]
            elif c == "n_comment":
                out[c] = (h64(c, idx) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out

    def supplier(self, idx, cols):
        key = idx.astype(np.int64) + 1
        out = {}
        for c in cols:
            if c == "s_suppkey":
                out[c] = key
            elif c == "s_nationkey":
                out[c] = uint_in(c, idx, 0, 24)
            elif c == "s_acctbal":
                out[c] = uint_in(c, idx, -99999, 999999)
            elif c == "s_name":
                out[c] = ("Supplier#", key)  # lazy formatted
            elif c == "s_address":
                out[c] = ("addr-s-", key)
            elif c == "s_phone":
                out[c] = ("phone", uint_in("s_nationkey", idx, 0, 24), h64(c, idx))
            elif c == "s_comment":
                out[c] = (h64(c, idx) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out

    def customer(self, idx, cols):
        key = idx.astype(np.int64) + 1
        out = {}
        for c in cols:
            if c == "c_custkey":
                out[c] = key
            elif c == "c_nationkey":
                out[c] = uint_in(c, idx, 0, 24)
            elif c == "c_acctbal":
                out[c] = uint_in(c, idx, -99999, 999999)
            elif c == "c_mktsegment":
                out[c] = (h64(c, idx) % np.uint64(5)).astype(np.int32)
            elif c == "c_name":
                out[c] = ("Customer#", key)
            elif c == "c_address":
                out[c] = ("addr-c-", key)
            elif c == "c_phone":
                out[c] = ("phone", uint_in("c_nationkey", idx, 0, 24), h64(c, idx))
            elif c == "c_comment":
                out[c] = (h64(c, idx) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out

    def part(self, idx, cols):
        key = idx.astype(np.int64) + 1
        out = {}
        for c in cols:
            if c == "p_partkey":
                out[c] = key
            elif c == "p_mfgr":
                # brand is within mfgr (Brand#MN where M = mfgr number)
                out[c] = (h64("p_mfgr", idx) % np.uint64(5)).astype(np.int32)
            elif c == "p_brand":
                m = (h64("p_mfgr", idx) % np.uint64(5)).astype(np.int64)
                b = (h64("p_brand", idx) % np.uint64(5)).astype(np.int64)
                out[c] = (m * 5 + b).astype(np.int32)
            elif c == "p_type":
                out[c] = (h64(c, idx) % np.uint64(len(P_TYPES))).astype(np.int32)
            elif c == "p_size":
                out[c] = uint_in(c, idx, 1, 50)
            elif c == "p_container":
                out[c] = (h64(c, idx) % np.uint64(len(CONTAINERS))).astype(np.int32)
            elif c == "p_retailprice":
                out[c] = _retail_price_cents(key)
            elif c == "p_name":
                out[c] = ("pname", key)
            elif c == "p_comment":
                out[c] = (h64(c, idx) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out

    def partsupp(self, idx, cols):
        # row i -> (part p = i//4, supplier slot i%4)
        p = (idx // 4).astype(np.int64) + 1
        slot = (idx % 4).astype(np.int64)
        out = {}
        for c in cols:
            if c == "ps_partkey":
                out[c] = p
            elif c == "ps_suppkey":
                out[c] = _ps_suppkey(p, slot, self.n["supplier"])
            elif c == "ps_availqty":
                out[c] = uint_in(c, idx, 1, 9999)
            elif c == "ps_supplycost":
                out[c] = uint_in(c, idx, 100, 100000)
            elif c == "ps_comment":
                out[c] = (h64(c, idx) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out

    def orders(self, idx, cols):
        j = idx.astype(np.int64)
        out = {}
        need_status = "o_orderstatus" in cols
        odate = EPOCH_1992 + uint_in("o_orderdate", j, 0, ORDER_DATE_SPAN - 1)
        for c in cols:
            if c == "o_orderkey":
                out[c] = _orderkey(j)
            elif c == "o_custkey":
                out[c] = _custkey_for_order(j, self.n["customer"])
            elif c == "o_orderdate":
                out[c] = odate.astype(np.int32)
            elif c == "o_totalprice":
                out[c] = uint_in(c, j, 100000, 50000000)
            elif c == "o_orderpriority":
                out[c] = (h64(c, j) % np.uint64(5)).astype(np.int32)
            elif c == "o_shippriority":
                out[c] = np.zeros(len(j), dtype=np.int64)
            elif c == "o_clerk":
                nclerk = max(1, int(1000 * self.sf))
                out[c] = ("Clerk#", uint_in(c, j, 1, nclerk))
            elif c == "o_comment":
                out[c] = (h64(c, j) % np.uint64(len(COMMENTS))).astype(np.int32)
        if need_status:
            # F if every line shipped on or before CURRENT_DATE, O if none
            # did, else P — computed from the same hashes lineitem uses
            counts = _line_count(j)
            all_f = np.ones(len(j), dtype=bool)
            all_o = np.ones(len(j), dtype=bool)
            for ln in range(7):
                has = counts > ln
                ship = odate + 1 + (
                    h64("l_shipdate", j * np.int64(8) + ln) % np.uint64(121)
                ).astype(np.int64)
                f = ship <= CURRENT_DATE
                all_f &= ~has | f
                all_o &= ~has | ~f
            status = np.where(all_f, 0, np.where(all_o, 1, 2)).astype(np.int32)
            out["o_orderstatus"] = status
        return out

    # -- lineitem (rows derived from order index space) -------------
    def lineitem_for_orders(self, j: np.ndarray, cols):
        counts = _line_count(j)
        total = int(counts.sum())
        oj = np.repeat(j, counts)  # order index per line row
        starts = np.cumsum(counts) - counts
        ln = np.arange(total, dtype=np.int64) - np.repeat(starts, counts)
        lid = oj * np.int64(8) + ln  # unique per-line counter
        out = {}
        odate = EPOCH_1992 + uint_in("o_orderdate", oj, 0, ORDER_DATE_SPAN - 1)
        ship = odate + 1 + (h64("l_shipdate", lid) % np.uint64(121)).astype(np.int64)
        npart = self.n["part"]
        partkey = 1 + (h64("l_partkey", lid) % np.uint64(npart)).astype(np.int64)
        qty = uint_in("l_quantity", lid, 1, 50)
        for c in cols:
            if c == "l_orderkey":
                out[c] = _orderkey(oj)
            elif c == "l_partkey":
                out[c] = partkey
            elif c == "l_suppkey":
                slot = (h64("l_supp_slot", lid) % np.uint64(4)).astype(np.int64)
                out[c] = _ps_suppkey(partkey, slot, self.n["supplier"])
            elif c == "l_linenumber":
                out[c] = ln + 1
            elif c == "l_quantity":
                out[c] = qty * 100  # decimal(12,2) integral quantities
            elif c == "l_extendedprice":
                out[c] = qty * _retail_price_cents(partkey)
            elif c == "l_discount":
                out[c] = uint_in(c, lid, 0, 10)
            elif c == "l_tax":
                out[c] = uint_in(c, lid, 0, 8)
            elif c == "l_shipdate":
                out[c] = ship.astype(np.int32)
            elif c == "l_commitdate":
                out[c] = (odate + uint_in(c, lid, 30, 90)).astype(np.int32)
            elif c == "l_receiptdate":
                out[c] = (ship + uint_in(c, lid, 1, 30)).astype(np.int32)
            elif c == "l_returnflag":
                receipt = ship + uint_in("l_receiptdate", lid, 1, 30)
                rnd = (h64(c, lid) % np.uint64(2)).astype(np.int32)  # A or R
                out[c] = np.where(receipt <= CURRENT_DATE, rnd * 2, 1).astype(
                    np.int32
                )  # codes: A=0,N=1,R=2
            elif c == "l_linestatus":
                out[c] = (ship > CURRENT_DATE).astype(np.int32)  # F=0, O=1
            elif c == "l_shipinstruct":
                out[c] = (h64(c, lid) % np.uint64(4)).astype(np.int32)
            elif c == "l_shipmode":
                out[c] = (h64(c, lid) % np.uint64(7)).astype(np.int32)
            elif c == "l_comment":
                out[c] = (h64(c, lid) % np.uint64(len(COMMENTS))).astype(np.int32)
        return out, total


def _format_lazy(spec, schema_type) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize a lazily-specified high-cardinality string column as
    (codes, dictionary).  Formatted-key specs (Supplier#N, phone) are
    distinct so codes are arange; pname DEDUPES its dictionary and
    remaps codes (names can repeat, and code equality must equal
    string equality)."""
    if spec[0] == "pname":
        _, keys = spec
        nw = np.uint64(len(P_NAME_WORDS))
        # 5 hash-chosen words per part (dbgen draws 5 distinct; hash draws
        # may rarely repeat a word within one name — selectivity of word
        # predicates is preserved to ~0.1%).  Names can collide across
        # parts (dbgen's do too), so the dictionary is DEDUPED and codes
        # remapped — code equality must equal string equality.
        picks = [
            (h64(f"p_name_{slot}", keys) % nw).astype(np.int64)
            for slot in range(5)
        ]
        W = P_NAME_WORDS
        index: dict = {}
        entries: list = []
        codes = np.empty(len(keys), dtype=np.int32)
        for i, (a, b, c, e, f) in enumerate(zip(*picks)):
            s = " ".join((W[a], W[b], W[c], W[e], W[f]))
            code = index.get(s)
            if code is None:
                code = len(entries)
                index[s] = code
                entries.append(s)
            codes[i] = code
        return codes, np.array(entries, dtype=object)
    elif spec[0] == "phone":
        _, cc, hh = spec
        n1 = (hh >> np.uint64(10)) % np.uint64(900) + np.uint64(100)
        n2 = (hh >> np.uint64(30)) % np.uint64(900) + np.uint64(100)
        n3 = (hh >> np.uint64(45)) % np.uint64(9000) + np.uint64(1000)
        d = np.array(
            [
                f"{10 + int(c)}-{int(a)}-{int(b)}-{int(x)}"
                for c, a, b, x in zip(cc, n1, n2, n3)
            ],
            dtype=object,
        )
    else:
        prefix, keys = spec
        if prefix.endswith("#"):
            d = np.array([f"{prefix}{int(k):09d}" for k in keys], dtype=object)
        else:
            d = np.array([f"{prefix}{int(k)}" for k in keys], dtype=object)
    codes = np.arange(len(d), dtype=np.int32)
    return codes, d


def generate(
    table: str,
    sf: float,
    split: int = 0,
    num_splits: int = 1,
    columns: Optional[Sequence[str]] = None,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], int]:
    """Generate one split of a table.

    Returns (values by column, dictionaries by column, row_count).
    Lineitem splits partition *order index space* so each split is
    self-contained (all lines of an order stay in one split).
    """
    schema = SCHEMAS[table]
    all_cols = [c for c, _ in schema]
    cols = list(columns) if columns is not None else all_cols
    for c in cols:
        if c not in all_cols:
            raise KeyError(f"{table}.{c}")
    g = _Gen(sf)
    base = "orders" if table == "lineitem" else table
    n = g.n[base]
    lo = (n * split) // num_splits
    hi = (n * (split + 1)) // num_splits
    idx = np.arange(lo, hi, dtype=np.int64)
    if table == "lineitem":
        # native (C++) fused generator when available; numpy fallback
        from . import native_gen

        native = native_gen.gen_lineitem(
            lo, hi, g.n["part"], g.n["supplier"], len(COMMENTS)
        )
        if native is not None:
            raw = {c: native[c] for c in cols}
            count = len(native["l_orderkey"])
        else:
            raw, count = g.lineitem_for_orders(idx, cols)
    else:
        raw = getattr(g, table)(idx, cols)
        count = hi - lo
    values: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}
    types = dict(schema)
    for c in cols:
        v = raw[c]
        if isinstance(v, tuple):  # lazy high-cardinality string
            codes, d = _format_lazy(v, types[c])
            values[c], dicts[c] = codes, d
        else:
            values[c] = v
            if types[c].is_dictionary:
                dicts[c] = _VOCABS[c]
    return values, dicts, count


def rows_to_pylist(table: str, sf: float, limit: int = 10) -> list:
    """Convenience for tests: first rows of a table as python tuples."""
    values, dicts, count = generate(table, sf)
    schema = SCHEMAS[table]
    page = Page(
        [
            Column(t, values[c][:limit], None, dicts.get(c))
            for c, t in schema
        ],
        min(limit, count),
        [c for c, _ in schema],
    )
    return page.to_pylist()


# --- SPI implementation ------------------------------------------------


class TpchMetadata(ConnectorMetadata):
    def __init__(self, sf: float):
        self.sf = sf

    def list_tables(self) -> List[str]:
        return list(SCHEMAS)

    def get_table_schema(self, table: str) -> TableSchema:
        return TableSchema(
            table, tuple(ColumnSchema(c, t) for c, t in SCHEMAS[table])
        )

    def get_table_statistics(self, table: str) -> TableStatistics:
        """Mirrors TpchMetadata's statistics support (plugin/trino-tpch
        .../statistics) — row counts and NDV estimates drive join ordering
        and unique-build-side detection.  Only true primary keys report
        distinct_count == row_count.  Cached per table: the memo
        optimizer reads these once per estimate across hundreds of
        alternatives (sf is fixed per connector)."""
        cache = getattr(self, "_stats_cache", None)
        if cache is None:
            cache = self._stats_cache = {}
        if table in cache:
            return cache[table]
        counts = _counts(self.sf)
        n = counts[table]
        pk = {
            "region": "r_regionkey", "nation": "n_nationkey",
            "supplier": "s_suppkey", "customer": "c_custkey",
            "part": "p_partkey", "orders": "o_orderkey",
        }.get(table)
        # FK cardinalities (approximate dbgen NDVs)
        fk_ndv = {
            "o_custkey": counts["customer"] * 2 / 3,
            "l_orderkey": float(counts["orders"]),
            "l_partkey": float(counts["part"]),
            "l_suppkey": float(counts["supplier"]),
            "ps_partkey": float(counts["part"]),
            "ps_suppkey": float(counts["supplier"]),
            "c_nationkey": 25.0,
            "s_nationkey": 25.0,
            "n_regionkey": 5.0,
        }
        # dbgen value-domain invariants (TPC-H spec 4.2.3: date windows,
        # quantity/discount/tax ranges; stored-scale for decimal lanes) —
        # range selectivities for the CBO (FilterStatsCalculator inputs)
        okey_max = float(_orderkey(np.array([counts["orders"] - 1]))[0]) + 7
        ranges = {
            "o_orderdate": (8035.0, 10440.0),   # 1992-01-01..1998-08-02
            "l_shipdate": (8036.0, 10561.0),    # orderdate+1..121
            "l_commitdate": (8065.0, 10530.0),  # orderdate+30..90
            "l_receiptdate": (8037.0, 10591.0),  # shipdate+1..30
            "l_quantity": (100.0, 5000.0),      # 1..50 (x100 lanes)
            # qty x retail price cents: [1x90000, 50x209900] — the
            # megakernel's interval proofs need this bound
            "l_extendedprice": (90000.0, 10495000.0),
            "l_discount": (0.0, 10.0),          # 0.00..0.10 (x100)
            "l_tax": (0.0, 8.0),                # 0.00..0.08 (x100)
            "l_linenumber": (1.0, 7.0),
            "o_orderkey": (1.0, okey_max),
            "l_orderkey": (1.0, okey_max),
            "o_custkey": (1.0, float(counts["customer"])),
            "c_custkey": (1.0, float(counts["customer"])),
            "p_partkey": (1.0, float(counts["part"])),
            "l_partkey": (1.0, float(counts["part"])),
            "ps_partkey": (1.0, float(counts["part"])),
            "s_suppkey": (1.0, float(counts["supplier"])),
            "l_suppkey": (1.0, float(counts["supplier"])),
            "ps_suppkey": (1.0, float(counts["supplier"])),
            "n_nationkey": (0.0, 24.0),
            "c_nationkey": (0.0, 24.0),
            "s_nationkey": (0.0, 24.0),
            "r_regionkey": (0.0, 4.0),
            "n_regionkey": (0.0, 4.0),
        }
        cols: Dict[str, ColumnStatistics] = {}
        for c, t in SCHEMAS[table]:
            lo, hi = ranges.get(c, (None, None))
            if c == pk:
                cols[c] = ColumnStatistics(
                    distinct_count=float(n), min_value=lo, max_value=hi
                )
            elif c in fk_ndv:
                cols[c] = ColumnStatistics(
                    distinct_count=min(fk_ndv[c], n),
                    min_value=lo, max_value=hi,
                )
            elif t.is_dictionary and c in _VOCABS:
                cols[c] = ColumnStatistics(
                    distinct_count=float(len(_VOCABS[c]))
                )
            elif lo is not None:
                cols[c] = ColumnStatistics(min_value=lo, max_value=hi)
        cache[table] = TableStatistics(float(n), cols)
        return cache[table]


class TpchSplitManager(SplitManager):
    """Reference: TpchSplitManager.java:40 — nodes x splitsPerNode."""

    def __init__(self, sf: float, connector=None):
        self.sf = sf
        self.connector = connector

    def get_splits(self, table: str, desired: int, constraint=None) -> List[Split]:
        n = _counts(self.sf)["orders" if table == "lineitem" else table]
        # honor the engine's desired parallelism down to rows-per-split
        # granularity so multi-node tests exercise real split distribution
        # at tiny SF (SET SESSION <catalog>.rows-per-split overrides)
        rows = 512
        if self.connector is not None:
            rows = int(
                self.connector.get_session_property("rows_per_split")
            )
        k = max(1, min(desired, (n + rows - 1) // rows))
        return [Split(table, i, k, {"sf": self.sf}) for i in range(k)]


class TpchPageSource(PageSource):
    def __init__(self, sf, split: Split, columns: Sequence[str]):
        self.sf = sf
        self.split = split
        self.columns = list(columns)
        self._dicts: Dict[str, np.ndarray] = {}

    def pages(self):
        values, dicts, count = generate(
            self.split.table, self.sf, self.split.ordinal, self.split.total,
            self.columns,
        )
        self._dicts = dicts
        types = dict(SCHEMAS[self.split.table])
        cols = [
            Column(types[c], values[c], None, dicts.get(c)) for c in self.columns
        ]
        yield Page(cols, count, self.columns)

    def dictionaries(self) -> Dict[str, np.ndarray]:
        # fixed vocabularies are known before generation; lazy (per-split)
        # dictionaries only after pages() ran
        types = dict(SCHEMAS[self.split.table])
        out = dict(self._dicts)
        for c in self.columns:
            if types[c].is_dictionary and c in _VOCABS and c not in out:
                out[c] = _VOCABS[c]
        return out


class TpchPageSourceProvider(PageSourceProvider):
    def __init__(self, sf: float):
        self.sf = sf

    def create_page_source(self, split: Split, columns) -> TpchPageSource:
        return TpchPageSource(self.sf, split, columns)


class TpchConnector(Connector):
    def __init__(self, name: str, sf: float):
        self.name = name
        self.sf = sf

    def metadata(self):
        return TpchMetadata(self.sf)

    def split_manager(self):
        return TpchSplitManager(self.sf, self)

    def session_property_metadata(self):
        from ..config import PropertyMetadata

        return {
            "rows_per_split": PropertyMetadata(
                "rows_per_split",
                "split granularity for the generator connector",
                int, 512,
            ),
        }

    def page_source_provider(self):
        return TpchPageSourceProvider(self.sf)

    def device_generation(self, table: str, cols, splits) -> Optional[dict]:
        """On-device generation spec for a contiguous split range, or None
        when any requested column needs host formatting / splits are
        non-contiguous (connectors/tpch_device.py; the TPU-resident analog
        of TpchPageSourceProvider's in-process row generation)."""
        from . import tpch_device

        if not splits or not tpch_device.supports(table, cols):
            return None
        tot = splits[0].total
        ords = sorted(s.ordinal for s in splits)
        if any(s.total != tot for s in splits):
            return None
        if ords != list(range(ords[0], ords[-1] + 1)):
            return None
        base = "orders" if table == "lineitem" else table
        nb = _counts(self.sf)[base]
        lo = (nb * ords[0]) // tot
        hi = (nb * (ords[-1] + 1)) // tot
        if table == "lineitem":
            count = tpch_device.lineitem_count(lo, hi)
        else:
            count = hi - lo
        types = dict(SCHEMAS[table])
        dicts = {
            c: _VOCABS[c]
            for c in cols
            if types[c].is_dictionary and c in _VOCABS
        }
        widths = {c: 4 if types[c].is_dictionary or types[c].name == "date"
                  else 8 for c in cols}
        return {
            "table": table, "lo": lo, "hi": hi, "sf": self.sf,
            "count": count, "dicts": dicts, "widths": widths,
        }


class TpchConnectorFactory(ConnectorFactory):
    """Reference: TpchConnectorFactory — config key tpch.scale-factor."""

    name = "tpch"

    def create(self, catalog_name: str, config: dict) -> TpchConnector:
        sf = float(config.get("tpch.scale-factor", 0.01))
        return TpchConnector(catalog_name, sf)
