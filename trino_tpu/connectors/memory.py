"""In-memory table connector.

Reference parity: plugin/trino-memory (MemoryConnector, MemoryMetadata,
MemoryPagesStore) — tables held as host numpy columns, used by engine
tests as a scriptable data source (the MockConnector/memory role).
Rows are inserted through the python API (create_table) since the engine's
DML surface is read-oriented for now.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .. import types as T
from ..page import Column, Page, column_from_pylist
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)


class _Store:
    def __init__(self):
        self.tables: Dict[str, Page] = {}
        self.schemas: Dict[str, TableSchema] = {}


class MemoryMetadata(ConnectorMetadata):
    def __init__(self, store: _Store):
        self.store = store

    def list_tables(self) -> List[str]:
        return list(self.store.tables)

    def get_table_schema(self, table: str) -> TableSchema:
        return self.store.schemas[table]

    def get_table_statistics(self, table: str) -> TableStatistics:
        page = self.store.tables[table]
        return TableStatistics(float(page.count), {})


class MemorySplitManager(SplitManager):
    def __init__(self, store: _Store):
        self.store = store

    def get_splits(self, table: str, desired: int, constraint=None) -> List[Split]:
        return [Split(table, 0, 1)]


class MemoryPageSource(PageSource):
    def __init__(self, store: _Store, split: Split, columns: Sequence[str]):
        self.store = store
        self.split = split
        self.columns = list(columns)

    def pages(self):
        page = self.store.tables[self.split.table]
        cols = [page.by_name(c) for c in self.columns]
        yield Page(cols, page.count, self.columns)

    def dictionaries(self) -> Dict[str, np.ndarray]:
        page = self.store.tables[self.split.table]
        out = {}
        for c in self.columns:
            col = page.by_name(c)
            if col.dictionary is not None:
                out[c] = col.dictionary
        return out


class MemoryPageSourceProvider(PageSourceProvider):
    def __init__(self, store: _Store):
        self.store = store

    def create_page_source(self, split: Split, columns) -> MemoryPageSource:
        return MemoryPageSource(self.store, split, columns)


class MemoryConnector(Connector):
    def __init__(self, name: str):
        self.name = name
        self.store = _Store()

    def create_table(self, name: str, schema, data: dict):
        """schema: list of (col, Type); data: col -> python values."""
        cols = [column_from_pylist(t, data[c]) for c, t in schema]
        counts = {len(c) for c in cols}
        assert len(counts) == 1
        self.store.tables[name] = Page(cols, counts.pop(), [c for c, _ in schema])
        self.store.schemas[name] = TableSchema(
            name, tuple(ColumnSchema(c, t) for c, t in schema)
        )

    def metadata(self):
        return MemoryMetadata(self.store)

    def split_manager(self):
        return MemorySplitManager(self.store)

    def page_source_provider(self):
        return MemoryPageSourceProvider(self.store)


class MemoryConnectorFactory(ConnectorFactory):
    name = "memory"

    def create(self, catalog_name: str, config: dict) -> MemoryConnector:
        return MemoryConnector(catalog_name)
