"""In-memory table connector.

Reference parity: plugin/trino-memory (MemoryConnector, MemoryMetadata,
MemoryPagesStore) — tables held as host numpy columns, used by engine
tests as a scriptable data source (the MockConnector/memory role).
Writable: CREATE TABLE [AS] / INSERT / DELETE flow through the PageSink
SPI; rows can also be loaded via the python API (create_table).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Column, Page, column_from_pylist
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSink,
    PageSinkProvider,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)


class _Store:
    def __init__(self):
        self.tables: Dict[str, Page] = {}
        self.schemas: Dict[str, TableSchema] = {}
        self.version = 0  # bumped on every write (scan-cache invalidation)
        # per-table change counters (Connector.data_version(table)): an
        # INSERT into A must not invalidate cached results scanning B
        self.versions: Dict[str, int] = {}
        # ANALYZE results keyed by the data_version they were collected
        # at; served only while the table hasn't been written since
        self.stats: Dict[str, Tuple[int, TableStatistics]] = {}

    def bump(self, table: str) -> None:
        self.version += 1
        self.versions[table] = self.versions.get(table, 0) + 1


class MemoryMetadata(ConnectorMetadata):
    def __init__(self, store: _Store):
        self.store = store

    def list_tables(self) -> List[str]:
        return list(self.store.tables)

    def get_table_schema(self, table: str) -> TableSchema:
        return self.store.schemas[table]

    def get_table_statistics(self, table: str) -> TableStatistics:
        entry = self.store.stats.get(table)
        if entry is not None:
            version, stats = entry
            if version == self.store.versions.get(table, 0):
                return stats
            del self.store.stats[table]  # DML since ANALYZE: stale
        page = self.store.tables[table]
        return TableStatistics(float(page.count), {})

    def store_table_statistics(
        self, table: str, stats: TableStatistics, data_version: int
    ) -> None:
        if table not in self.store.tables:
            raise KeyError(f"table {table} does not exist")
        self.store.stats[table] = (int(data_version), stats)

    # -- writes (MemoryMetadata.beginCreateTable/beginInsert analog) ----
    def create_table(self, schema: TableSchema) -> None:
        if schema.name in self.store.tables:
            raise ValueError(f"table {schema.name} already exists")
        self.store.bump(schema.name)
        cols = [column_from_pylist(c.type, []) for c in schema.columns]
        self.store.tables[schema.name] = Page(
            cols, 0, [c.name for c in schema.columns]
        )
        self.store.schemas[schema.name] = schema

    def drop_table(self, table: str) -> None:
        if table not in self.store.tables:
            raise KeyError(f"table {table} does not exist")
        self.store.bump(table)
        del self.store.tables[table]
        del self.store.schemas[table]


class MemorySplitManager(SplitManager):
    def __init__(self, store: _Store):
        self.store = store

    def get_splits(self, table: str, desired: int, constraint=None) -> List[Split]:
        return [Split(table, 0, 1)]


class MemoryPageSource(PageSource):
    def __init__(self, store: _Store, split: Split, columns: Sequence[str]):
        self.store = store
        self.split = split
        self.columns = list(columns)

    def pages(self):
        page = self.store.tables[self.split.table]
        cols = [page.by_name(c) for c in self.columns]
        yield Page(cols, page.count, self.columns)

    def dictionaries(self) -> Dict[str, np.ndarray]:
        page = self.store.tables[self.split.table]
        out = {}
        for c in self.columns:
            col = page.by_name(c)
            if col.dictionary is not None:
                out[c] = col.dictionary
        return out


class MemoryPageSourceProvider(PageSourceProvider):
    def __init__(self, store: _Store):
        self.store = store

    def create_page_source(self, split: Split, columns) -> MemoryPageSource:
        return MemoryPageSource(self.store, split, columns)


class MemoryPageSink(PageSink):
    """MemoryPagesStore.add analog.  Buffers appended pages as python
    values and rebuilds the stored columns at finish() — re-encoding
    unifies per-page varchar dictionaries (correctness over speed: this
    is the test connector, like the reference's trino-memory)."""

    def __init__(self, store: _Store, table: str, columns, overwrite: bool):
        self.store = store
        self.table = table
        self.columns = list(columns)
        self.overwrite = overwrite
        self.buffered: List[list] = [[] for _ in self.columns]
        self.rows = 0

    def append(self, page: Page) -> None:
        for i, name in enumerate(self.columns):
            self.buffered[i].extend(page.by_name(name).to_python(page.count))
        self.rows += page.count

    def finish(self) -> int:
        schema = self.store.schemas[self.table]
        old = self.store.tables[self.table]
        data: Dict[str, list] = {}
        for c in schema.columns:
            prior = (
                [] if self.overwrite
                else old.by_name(c.name).to_python(old.count)
            )
            try:
                idx = self.columns.index(c.name)
                incoming = self.buffered[idx]
            except ValueError:
                incoming = [None] * self.rows  # unmentioned column -> NULL
            data[c.name] = prior + incoming
        cols = [
            column_from_pylist(c.type, data[c.name]) for c in schema.columns
        ]
        self.store.tables[self.table] = Page(
            cols, len(data[schema.columns[0].name]),
            [c.name for c in schema.columns],
        )
        self.store.bump(self.table)
        return self.rows


class MemoryPageSinkProvider(PageSinkProvider):
    def __init__(self, store: _Store):
        self.store = store

    def create_sink(self, table: str, columns, overwrite: bool = False):
        if table not in self.store.tables:
            raise KeyError(f"table {table} does not exist")
        return MemoryPageSink(self.store, table, columns, overwrite)


class MemoryConnector(Connector):
    def __init__(self, name: str):
        self.name = name
        self.store = _Store()

    def data_version(self, table=None) -> int:
        if table is None:
            return self.store.version
        return self.store.versions.get(table, 0)

    def create_table(self, name: str, schema, data: dict):
        """schema: list of (col, Type); data: col -> python values."""
        self.store.bump(name)
        cols = [column_from_pylist(t, data[c]) for c, t in schema]
        counts = {len(c) for c in cols}
        assert len(counts) == 1
        self.store.tables[name] = Page(cols, counts.pop(), [c for c, _ in schema])
        self.store.schemas[name] = TableSchema(
            name, tuple(ColumnSchema(c, t) for c, t in schema)
        )

    def metadata(self):
        return MemoryMetadata(self.store)

    def split_manager(self):
        return MemorySplitManager(self.store)

    def page_source_provider(self):
        return MemoryPageSourceProvider(self.store)

    def page_sink_provider(self):
        return MemoryPageSinkProvider(self.store)


class MemoryConnectorFactory(ConnectorFactory):
    name = "memory"

    def create(self, catalog_name: str, config: dict) -> MemoryConnector:
        return MemoryConnector(catalog_name)
