"""System catalog: cluster/runtime introspection as SQL tables.

Reference parity: the system tables the engine itself serves —
system.runtime.queries / system.runtime.nodes (connector/system/ in
trino-main: QuerySystemTable, NodeSystemTable), system.metadata.catalogs,
system.jdbc.tables/columns — plus the JMX-as-SQL idea of plugin/trino-jmx
(metrics queryable through the same scan path).  Tables snapshot live
engine state at scan time.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .. import types as T
from ..page import Page, column_from_pylist
from ..spi import (
    ColumnSchema,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)

SCHEMAS: Dict[str, List] = {
    "catalogs": [("catalog_name", T.VARCHAR), ("connector_name", T.VARCHAR)],
    "tables": [("table_catalog", T.VARCHAR), ("table_name", T.VARCHAR)],
    "columns": [
        ("table_catalog", T.VARCHAR),
        ("table_name", T.VARCHAR),
        ("column_name", T.VARCHAR),
        ("data_type", T.VARCHAR),
    ],
    "queries": [
        ("query_id", T.VARCHAR),
        ("state", T.VARCHAR),
        ("query", T.VARCHAR),
        ("user", T.VARCHAR),
        ("created", T.DOUBLE),
        ("finished", T.DOUBLE),
        ("rows", T.BIGINT),
        ("error", T.VARCHAR),
    ],
    "nodes": [
        ("node_id", T.VARCHAR),
        ("http_uri", T.VARCHAR),
        # distributed: lifecycle state machine (server/discovery.py)
        # ACTIVE/SUSPECT/DRAINING/DRAINED/GONE; local session: "active"
        ("state", T.VARCHAR),
        ("state_age_s", T.DOUBLE),
        # device-fault supervisor health (runtime/supervisor.py):
        # ACTIVE/DEGRADED/QUARANTINED + strikes toward the blacklist
        ("device_state", T.VARCHAR),
        ("device_strikes", T.BIGINT),
        # multi-host topology (distributed/topology.py): which host the
        # node lives on, its process index in the global mesh, and how
        # many local devices its slice owns; NULL for plain workers
        ("host", T.VARCHAR),
        ("process_index", T.BIGINT),
        ("local_devices", T.BIGINT),
    ],
    "views": [
        ("table_catalog", T.VARCHAR),
        ("table_name", T.VARCHAR),
        ("view_definition", T.VARCHAR),
    ],
    "session_properties": [
        ("name", T.VARCHAR),
        ("value", T.VARCHAR),
        ("default", T.VARCHAR),
    ],
    # one row per cache tier (result_cache / compile_cache / scan_cache);
    # backed by the session CacheManager (cache/__init__._ROW_COLUMNS)
    "caches": [
        ("name", T.VARCHAR),
        ("hits", T.BIGINT),
        ("misses", T.BIGINT),
        ("puts", T.BIGINT),
        ("evictions", T.BIGINT),
        ("entries", T.BIGINT),
        ("bytes", T.BIGINT),
        ("max_bytes", T.BIGINT),
        ("heals", T.BIGINT),
        ("invalidations", T.BIGINT),
    ],
    # one row per committed lakehouse snapshot across every mounted
    # catalog whose connector exposes snapshots_rows() (duck-typed like
    # the rest of this table's feeds); parent_id -1 marks the root
    "snapshots": [
        ("catalog", T.VARCHAR),
        ("table_name", T.VARCHAR),
        ("snapshot_id", T.BIGINT),
        ("parent_id", T.BIGINT),
        ("operation", T.VARCHAR),
        ("data_files", T.BIGINT),
        ("rows", T.BIGINT),
        ("is_current", T.BOOLEAN),
        ("committed_at_us", T.BIGINT),
    ],
    # one row per (node, pool): the cluster memory view — the session's
    # LocalMemoryManager plus every heartbeat-announced worker snapshot
    # held by the coordinator ClusterMemoryManager (MemoryPool MBeans /
    # the reference's memory UI surface)
    "memory": [
        ("node_id", T.VARCHAR),
        ("pool", T.VARCHAR),
        ("size_bytes", T.BIGINT),
        ("reserved_bytes", T.BIGINT),
        ("free_bytes", T.BIGINT),
        ("queries", T.BIGINT),
        ("blocked_queries", T.BIGINT),
    ],
    # one row per resource group in the coordinator's tree
    # (server/resource_groups.py): live queued/running/shed state plus
    # the scheduling configuration the arbiter runs on
    "resource_groups": [
        ("name", T.VARCHAR),
        ("scheduling_policy", T.VARCHAR),
        ("scheduling_weight", T.BIGINT),
        ("running", T.BIGINT),
        ("queued", T.BIGINT),
        ("hard_concurrency_limit", T.BIGINT),
        ("max_queued", T.BIGINT),
        ("queue_deadline_s", T.DOUBLE),
        ("memory_share", T.DOUBLE),
        ("memory_usage_bytes", T.BIGINT),
        ("soft_memory_limit_bytes", T.BIGINT),
        ("decayed_cost", T.DOUBLE),
        ("started_total", T.BIGINT),
        ("shed_total", T.BIGINT),
    ],
    # one row per ANALYZEd table (the session's analyze registry): when
    # stats were collected, over which columns, and at which data_version
    "table_stats": [
        ("catalog", T.VARCHAR),
        ("table_name", T.VARCHAR),
        ("columns", T.VARCHAR),
        ("row_count", T.DOUBLE),
        ("data_version", T.VARCHAR),
        ("analyzed_at", T.DOUBLE),
        ("duration_s", T.DOUBLE),
    ],
    # one row per kernel digest from the last ledger-enabled query's HBM
    # bandwidth accounting (obs/bandwidth.py; session.last_kernel_profile)
    "kernel_bandwidth": [
        ("kernel", T.VARCHAR),
        ("mode", T.VARCHAR),
        ("task_id", T.VARCHAR),
        ("executions", T.BIGINT),
        ("input_bytes", T.BIGINT),
        ("output_bytes", T.BIGINT),
        ("intermediate_bytes", T.BIGINT),
        ("total_bytes", T.BIGINT),
        ("device_wall_s", T.DOUBLE),
        ("gbps", T.DOUBLE),
        ("roofline_pct", T.DOUBLE),
    ],
    # the in-memory tail of the dispatch flight recorder
    # (obs/flight_recorder.py via the process device supervisor) —
    # seq-paired dispatch/complete/fault records, oldest first
    "flight_recorder": [
        ("seq", T.BIGINT),
        ("record_type", T.VARCHAR),
        ("kernel", T.VARCHAR),
        ("mode", T.VARCHAR),
        ("query_id", T.VARCHAR),
        ("task_id", T.VARCHAR),
        ("node_id", T.VARCHAR),
        ("shapes", T.VARCHAR),
        ("hbm_reserved_bytes", T.BIGINT),
        ("hbm_peak_bytes", T.BIGINT),
        ("wall_s", T.DOUBLE),
        ("fault_kind", T.VARCHAR),
        ("error", T.VARCHAR),
        ("ts", T.DOUBLE),
    ],
    # one row per operator frame of the last instrumented execution
    # (EXPLAIN ANALYZE / operator_stats=true; session.last_timeline) —
    # the operator/OperatorStats.java "as SQL" surface
    "operator_stats": [
        ("operator_id", T.BIGINT),
        ("plan_node_id", T.VARCHAR),
        ("operator_type", T.VARCHAR),
        ("input_rows", T.BIGINT),
        ("input_bytes", T.BIGINT),
        ("output_rows", T.BIGINT),
        ("output_bytes", T.BIGINT),
        ("wall_s", T.DOUBLE),
        ("device_wall_s", T.DOUBLE),
        ("host_wall_s", T.DOUBLE),
        ("blocked_memory_s", T.DOUBLE),
        ("blocked_exchange_s", T.DOUBLE),
        ("estimated_rows", T.DOUBLE),
        ("calls", T.BIGINT),
    ],
    # one row per completed query in the persisted history store
    # (obs/history.py): survives coordinator restart up to the torn tail
    "completed_queries": [
        ("query_id", T.VARCHAR),
        ("state", T.VARCHAR),
        ("query", T.VARCHAR),
        ("user", T.VARCHAR),
        ("created", T.DOUBLE),
        ("finished", T.DOUBLE),
        ("rows", T.BIGINT),
        ("wall_s", T.DOUBLE),
        ("error", T.VARCHAR),
        ("error_code", T.VARCHAR),
        ("tenant", T.VARCHAR),
        ("plan_signature", T.VARCHAR),
        ("operators", T.BIGINT),
    ],
    # the in-memory tail of the engine-wide incident journal
    # (obs/journal.py): every subsystem's typed, query/task/node-
    # correlated anomaly events, oldest first
    "events": [
        ("event_id", T.BIGINT),
        ("event_type", T.VARCHAR),
        ("query_id", T.VARCHAR),
        ("task_id", T.VARCHAR),
        ("node_id", T.VARCHAR),
        ("severity", T.VARCHAR),
        ("detail", T.VARCHAR),
        ("ts", T.DOUBLE),
    ],
    # the in-memory tail of the engine-wide compile observatory
    # (obs/compile_observatory.py): one row per trace/compile event,
    # including worker events ingested via the announcement piggyback
    "compiles": [
        ("compile_id", T.BIGINT),
        ("kernel", T.VARCHAR),
        ("family", T.VARCHAR),
        ("cause", T.VARCHAR),
        ("mode", T.VARCHAR),
        ("shapes", T.VARCHAR),
        ("actual_rows", T.BIGINT),
        ("padded_rows", T.BIGINT),
        ("compile_wall_s", T.DOUBLE),
        ("query_id", T.VARCHAR),
        ("task_id", T.VARCHAR),
        ("node_id", T.VARCHAR),
        ("ts", T.DOUBLE),
    ],
    # the shape census: one row per (kernel family, pow2 row bucket) —
    # the observed traffic-shape distribution scripts/bucket_ladder.py
    # turns into a padding-ladder recommendation
    "shape_census": [
        ("family", T.VARCHAR),
        ("bucket", T.BIGINT),
        ("count", T.BIGINT),
        ("min_rows", T.BIGINT),
        ("max_rows", T.BIGINT),
        ("total_rows", T.BIGINT),
    ],
    # one row per query-doctor verdict (obs/doctor.py finalize pass):
    # the ranked causal root-cause report, newest last
    "diagnoses": [
        ("query_id", T.VARCHAR),
        ("verdict", T.VARCHAR),
        ("root_cause", T.VARCHAR),
        ("summary", T.VARCHAR),
        ("error_code", T.VARCHAR),
        ("event_ids", T.VARCHAR),
        ("findings", T.BIGINT),
        ("wall_s", T.DOUBLE),
        ("ts", T.DOUBLE),
    ],
    # the serving observatory's workload census (obs/serving_observatory):
    # one row per profiled canonical plan signature — arrival rate,
    # latency percentiles, observed device/host cost, estimate drift and
    # result-cache tallies, busiest shape first
    "plan_signatures": [
        ("signature", T.VARCHAR),
        ("tenant", T.VARCHAR),
        ("count", T.BIGINT),
        ("rate_per_s", T.DOUBLE),
        ("p50_s", T.DOUBLE),
        ("p95_s", T.DOUBLE),
        ("p99_s", T.DOUBLE),
        ("device_wall_s", T.DOUBLE),
        ("host_wall_s", T.DOUBLE),
        ("drift_ratio", T.DOUBLE),
        ("cache_hits", T.BIGINT),
        ("cache_misses", T.BIGINT),
        ("families", T.BIGINT),
        ("last_ts", T.DOUBLE),
    ],
    # per-node warmth per signature: which nodes hold warm compiled
    # programs for a signature's kernel families (per-family census off
    # worker announcements) or its fragment-result-cache entry — the
    # locality-aware dispatcher's input table
    "signature_affinity": [
        ("signature", T.VARCHAR),
        ("node_id", T.VARCHAR),
        ("warm_families", T.BIGINT),
        ("families_total", T.BIGINT),
        ("result_cache", T.BIGINT),
        ("score", T.DOUBLE),
    ],
    # per-tenant SLO compliance: declared objectives plus live fast/slow
    # window burn rates over the tenant's latency samples
    "slos": [
        ("tenant", T.VARCHAR),
        ("latency_target_s", T.DOUBLE),
        ("error_budget", T.DOUBLE),
        ("fast_window_s", T.DOUBLE),
        ("slow_window_s", T.DOUBLE),
        ("fast_burn_rate", T.DOUBLE),
        ("slow_burn_rate", T.DOUBLE),
        ("peak_fast_burn", T.DOUBLE),
        ("violations_total", T.BIGINT),
        ("observed_total", T.BIGINT),
        ("burn_events", T.BIGINT),
        ("p50_s", T.DOUBLE),
        ("p95_s", T.DOUBLE),
        ("p99_s", T.DOUBLE),
    ],
    # one row per metric series from the process-global MetricsRegistry —
    # the plugin/trino-jmx "metrics as SQL" surface; histograms expose
    # interpolated p50/p95/p99 alongside the observation count
    "metrics": [
        ("name", T.VARCHAR),
        ("kind", T.VARCHAR),
        ("labels", T.VARCHAR),
        ("value", T.DOUBLE),
        ("p50", T.DOUBLE),
        ("p95", T.DOUBLE),
        ("p99", T.DOUBLE),
    ],
}


class _SystemSource:
    """Pulls the live rows for one system table from the owning session."""

    def __init__(self, session):
        self.session = session

    def rows(self, table: str) -> Dict[str, list]:
        s = self.session
        if table == "catalogs":
            names = [n for n in s.catalogs.names()]
            return {
                "catalog_name": names,
                "connector_name": [
                    type(s.catalogs.get(n)).__name__ for n in names
                ],
            }
        if table == "tables":
            cats, tabs = [], []
            for c in s.catalogs.names():
                try:
                    for t in s.catalogs.get(c).metadata().list_tables():
                        cats.append(c)
                        tabs.append(t)
                except NotImplementedError:
                    pass
            for (c, v) in sorted(getattr(s.metadata, "views", {})):
                cats.append(c)
                tabs.append(v)
            return {"table_catalog": cats, "table_name": tabs}
        if table == "views":
            views = sorted(
                getattr(s.metadata, "views", {}).items()
            )
            return {
                "table_catalog": [c for (c, _n), _v in views],
                "table_name": [n for (_c, n), _v in views],
                "view_definition": [v.original_sql for _k, v in views],
            }
        if table == "columns":
            out = {"table_catalog": [], "table_name": [],
                   "column_name": [], "data_type": []}
            for c in s.catalogs.names():
                md = s.catalogs.get(c).metadata()
                try:
                    tables = md.list_tables()
                except NotImplementedError:
                    continue
                for t in tables:
                    for col in md.get_table_schema(t).columns:
                        out["table_catalog"].append(c)
                        out["table_name"].append(t)
                        out["column_name"].append(col.name)
                        out["data_type"].append(str(col.type))
            return out
        if table == "queries":
            hist = list(getattr(s, "query_history", ()))
            return {
                "query_id": [h["query_id"] for h in hist],
                "state": [h["state"] for h in hist],
                "query": [h["sql"][:200] for h in hist],
                "user": [h.get("user") or "user" for h in hist],
                "created": [h["created"] for h in hist],
                "finished": [h.get("finished") for h in hist],
                "rows": [h.get("rows", 0) for h in hist],
                "error": [h.get("error") for h in hist],
            }
        if table == "nodes":
            def device_cols(dev):
                if not dev:
                    return "ACTIVE", 0
                strikes = sum(
                    int(d.get("strikes", 0))
                    for d in (dev.get("devices") or [])
                )
                return dev.get("state", "ACTIVE"), strikes

            nodes = []
            nm = getattr(s, "node_manager", None)
            if nm is not None:
                import time as _time

                # discovery stamps state_since with time.time()
                now = _time.time()
                for snap in nm.nodes_snapshot():
                    dstate, strikes = device_cols(snap.get("device"))
                    nodes.append(
                        (snap["nodeId"], snap["uri"], snap["state"],
                         max(now - float(snap["stateSince"] or now), 0.0),
                         dstate, strikes, snap.get("host"),
                         snap.get("processIndex"),
                         snap.get("localDevices"))
                    )
            else:
                sup = getattr(s, "device_supervisor", None)
                dstate, strikes = device_cols(
                    sup.snapshot() if sup is not None else None
                )
                nodes.append(("local", "local://", "active", 0.0,
                              dstate, strikes, None, None, None))
            return {
                "node_id": [n[0] for n in nodes],
                "http_uri": [n[1] for n in nodes],
                "state": [n[2] for n in nodes],
                "state_age_s": [n[3] for n in nodes],
                "device_state": [n[4] for n in nodes],
                "device_strikes": [n[5] for n in nodes],
                "host": [n[6] for n in nodes],
                "process_index": [n[7] for n in nodes],
                "local_devices": [n[8] for n in nodes],
            }
        if table == "session_properties":
            rows = s.properties.show()
            return {
                "name": [r[0] for r in rows],
                "value": [r[1] for r in rows],
                "default": [r[2] for r in rows],
            }
        if table == "snapshots":
            out = {
                "catalog": [], "table_name": [], "snapshot_id": [],
                "parent_id": [], "operation": [], "data_files": [],
                "rows": [], "is_current": [], "committed_at_us": [],
            }
            for c in s.catalogs.names():
                conn = s.catalogs.get(c)
                if not hasattr(conn, "snapshots_rows"):
                    continue
                for (t, snap, parent, op, nfiles, nrows, cur,
                     ts) in conn.snapshots_rows():
                    out["catalog"].append(c)
                    out["table_name"].append(t)
                    out["snapshot_id"].append(snap)
                    out["parent_id"].append(parent)
                    out["operation"].append(op)
                    out["data_files"].append(nfiles)
                    out["rows"].append(nrows)
                    out["is_current"].append(bool(cur))
                    out["committed_at_us"].append(ts)
            return out
        if table == "caches":
            mgr = getattr(s, "caches", None)
            stats = mgr.stats_rows() if mgr is not None else []
            return {
                c: [r.get(c) for r in stats]
                for c, _t in SCHEMAS["caches"]
            }
        if table == "memory":
            snaps = []
            mm = getattr(s, "memory_manager", None)
            if mm is not None:
                snaps.append(mm.snapshot())
            cm = getattr(s, "cluster_memory", None)
            if cm is not None:
                local_id = snaps[0]["nodeId"] if snaps else None
                snaps.extend(
                    n for n in cm.nodes_view()
                    if n.get("nodeId") != local_id
                )
            out = {c: [] for c, _t in SCHEMAS["memory"]}
            for snap in snaps:
                blocked = len(snap.get("blocked") or {})
                for pool, p in (snap.get("pools") or {}).items():
                    out["node_id"].append(snap.get("nodeId", "local"))
                    out["pool"].append(pool)
                    out["size_bytes"].append(int(p.get("size", 0)))
                    out["reserved_bytes"].append(int(p.get("reserved", 0)))
                    out["free_bytes"].append(int(p.get("free", 0)))
                    out["queries"].append(len(p.get("byQuery") or {}))
                    out["blocked_queries"].append(blocked)
            return out
        if table == "resource_groups":
            mgr = getattr(s, "resource_group_manager", None)
            stats = mgr.info() if mgr is not None else []
            return {
                "name": [g["name"] for g in stats],
                "scheduling_policy": [g["schedulingPolicy"] for g in stats],
                "scheduling_weight": [g["schedulingWeight"] for g in stats],
                "running": [g["running"] for g in stats],
                "queued": [g["queued"] for g in stats],
                "hard_concurrency_limit": [
                    g["hardConcurrencyLimit"] for g in stats
                ],
                "max_queued": [g["maxQueued"] for g in stats],
                "queue_deadline_s": [g["queueDeadlineS"] for g in stats],
                "memory_share": [g["memoryShare"] for g in stats],
                "memory_usage_bytes": [
                    g["memoryUsageBytes"] for g in stats
                ],
                "soft_memory_limit_bytes": [
                    g["softMemoryLimitBytes"] for g in stats
                ],
                "decayed_cost": [g["decayedCost"] for g in stats],
                "started_total": [g["startedTotal"] for g in stats],
                "shed_total": [g["shedTotal"] for g in stats],
            }
        if table == "table_stats":
            entries = sorted(
                getattr(s, "analyzed_tables", {}).values(),
                key=lambda e: (e["catalog"], e["table"]),
            )
            return {
                "catalog": [e["catalog"] for e in entries],
                "table_name": [e["table"] for e in entries],
                "columns": [", ".join(e["columns"]) for e in entries],
                "row_count": [e["row_count"] for e in entries],
                "data_version": [str(e["data_version"]) for e in entries],
                "analyzed_at": [e["analyzed_at"] for e in entries],
                "duration_s": [e["duration_s"] for e in entries],
            }
        if table == "kernel_bandwidth":
            prof = getattr(s, "last_kernel_profile", None) or {}
            entries = prof.get("bandwidth") or []
            return {
                "kernel": [e["kernel"] for e in entries],
                "mode": [e["mode"] for e in entries],
                "task_id": [e.get("taskId", "") for e in entries],
                "executions": [e["executions"] for e in entries],
                "input_bytes": [e["inputBytes"] for e in entries],
                "output_bytes": [e["outputBytes"] for e in entries],
                "intermediate_bytes": [
                    e["intermediateBytes"] for e in entries
                ],
                "total_bytes": [e["totalBytes"] for e in entries],
                "device_wall_s": [e["deviceWallS"] for e in entries],
                "gbps": [e["gbps"] for e in entries],
                "roofline_pct": [e["rooflinePct"] for e in entries],
            }
        if table == "flight_recorder":
            import json as _json

            sup = getattr(s, "device_supervisor", None)
            rec = getattr(sup, "flight_recorder", None)
            tail = rec.tail() if rec is not None else []
            return {
                "seq": [r.get("seq", 0) for r in tail],
                "record_type": [r.get("recordType", "") for r in tail],
                "kernel": [r.get("kernel", "") for r in tail],
                "mode": [r.get("mode", "") for r in tail],
                "query_id": [r.get("queryId", "") for r in tail],
                "task_id": [r.get("taskId", "") for r in tail],
                "node_id": [r.get("nodeId", "") for r in tail],
                "shapes": [
                    _json.dumps(r.get("shapes") or {}, sort_keys=True)
                    for r in tail
                ],
                "hbm_reserved_bytes": [
                    int(r.get("hbmReservedBytes") or 0) for r in tail
                ],
                "hbm_peak_bytes": [
                    int(r.get("hbmPeakBytes") or 0) for r in tail
                ],
                "wall_s": [float(r.get("wallS") or 0.0) for r in tail],
                "fault_kind": [r.get("faultKind", "") for r in tail],
                "error": [r.get("error", "") for r in tail],
                "ts": [float(r.get("ts") or 0.0) for r in tail],
            }
        if table == "operator_stats":
            tl = getattr(s, "last_timeline", None) or {}
            frames = tl.get("operators") or []
            return {
                "operator_id": [
                    int(f.get("operatorId") or 0) for f in frames
                ],
                "plan_node_id": [
                    str(f.get("planNodeId") or "") for f in frames
                ],
                "operator_type": [
                    f.get("operatorType", "") for f in frames
                ],
                "input_rows": [
                    int(f.get("inputRows") or 0) for f in frames
                ],
                "input_bytes": [
                    int(f.get("inputBytes") or 0) for f in frames
                ],
                "output_rows": [
                    int(f.get("outputRows") or 0) for f in frames
                ],
                "output_bytes": [
                    int(f.get("outputBytes") or 0) for f in frames
                ],
                "wall_s": [
                    float(f.get("wallS") or 0.0) for f in frames
                ],
                "device_wall_s": [
                    float(f.get("deviceWallS") or 0.0) for f in frames
                ],
                "host_wall_s": [
                    float(f.get("hostWallS") or 0.0) for f in frames
                ],
                "blocked_memory_s": [
                    float(f.get("blockedMemoryS") or 0.0) for f in frames
                ],
                "blocked_exchange_s": [
                    float(f.get("blockedExchangeS") or 0.0)
                    for f in frames
                ],
                "estimated_rows": [
                    f.get("estimatedRows") for f in frames
                ],
                "calls": [int(f.get("calls") or 0) for f in frames],
            }
        if table == "completed_queries":
            hist = getattr(s, "history", None)
            recs = hist.completed() if hist is not None else []
            return {
                "query_id": [r.get("queryId") for r in recs],
                "state": [r.get("state") for r in recs],
                "query": [(r.get("sql") or "")[:200] for r in recs],
                "user": [r.get("user") or "user" for r in recs],
                "created": [r.get("created") for r in recs],
                "finished": [r.get("finished") for r in recs],
                "rows": [int(r.get("rows") or 0) for r in recs],
                "wall_s": [float(r.get("wallS") or 0.0) for r in recs],
                "error": [r.get("error") for r in recs],
                "error_code": [r.get("errorCode") or "" for r in recs],
                "tenant": [r.get("tenant") or "" for r in recs],
                "plan_signature": [
                    r.get("planSignature") or "" for r in recs
                ],
                "operators": [
                    len(r.get("operators") or ()) for r in recs
                ],
            }
        if table == "events":
            import json as _json

            from ..obs import journal as _journal

            tail = _journal.get_journal().tail()
            return {
                "event_id": [int(e.get("eventId") or 0) for e in tail],
                "event_type": [e.get("eventType", "") for e in tail],
                "query_id": [e.get("queryId", "") for e in tail],
                "task_id": [e.get("taskId", "") for e in tail],
                "node_id": [e.get("nodeId", "") for e in tail],
                "severity": [e.get("severity", "") for e in tail],
                "detail": [
                    _json.dumps(e.get("detail") or {}, sort_keys=True)
                    for e in tail
                ],
                "ts": [float(e.get("ts") or 0.0) for e in tail],
            }
        if table == "compiles":
            import json as _json

            from ..obs import compile_observatory as _co

            tail = _co.get_observatory().tail()
            return {
                "compile_id": [int(e.get("compileId") or 0) for e in tail],
                "kernel": [e.get("kernel", "") for e in tail],
                "family": [e.get("family", "") for e in tail],
                "cause": [e.get("cause", "") for e in tail],
                "mode": [e.get("mode", "") for e in tail],
                "shapes": [
                    _json.dumps(e.get("shapes") or {}, sort_keys=True)
                    for e in tail
                ],
                "actual_rows": [
                    int(e.get("actualRows") or 0) for e in tail
                ],
                "padded_rows": [
                    int(e.get("paddedRows") or 0) for e in tail
                ],
                "compile_wall_s": [
                    float(e.get("compileWallS") or 0.0) for e in tail
                ],
                "query_id": [e.get("queryId", "") for e in tail],
                "task_id": [e.get("taskId", "") for e in tail],
                "node_id": [e.get("nodeId", "") for e in tail],
                "ts": [float(e.get("ts") or 0.0) for e in tail],
            }
        if table == "shape_census":
            from ..obs import compile_observatory as _co

            recs = _co.get_observatory().merged_census().rows()
            return {
                "family": [r["family"] for r in recs],
                "bucket": [r["bucket"] for r in recs],
                "count": [r["count"] for r in recs],
                "min_rows": [r["minRows"] for r in recs],
                "max_rows": [r["maxRows"] for r in recs],
                "total_rows": [r["totalRows"] for r in recs],
            }
        if table == "plan_signatures":
            from ..obs import serving_observatory as _so

            recs = _so.get_observatory().signature_rows()
            return {
                "signature": [r["signature"] for r in recs],
                "tenant": [r["tenant"] for r in recs],
                "count": [int(r["count"]) for r in recs],
                "rate_per_s": [float(r["ratePerS"]) for r in recs],
                "p50_s": [float(r["p50S"]) for r in recs],
                "p95_s": [float(r["p95S"]) for r in recs],
                "p99_s": [float(r["p99S"]) for r in recs],
                "device_wall_s": [
                    float(r["deviceWallS"]) for r in recs
                ],
                "host_wall_s": [float(r["hostWallS"]) for r in recs],
                "drift_ratio": [float(r["driftRatio"]) for r in recs],
                "cache_hits": [int(r["cacheHits"]) for r in recs],
                "cache_misses": [int(r["cacheMisses"]) for r in recs],
                "families": [len(r["families"]) for r in recs],
                "last_ts": [float(r["lastTs"]) for r in recs],
            }
        if table == "signature_affinity":
            from ..obs import serving_observatory as _so

            recs = _so.get_observatory().affinity_rows(
                local_node_id=getattr(s, "serving_node_id", "") or "local"
            )
            return {
                "signature": [r["signature"] for r in recs],
                "node_id": [r["nodeId"] for r in recs],
                "warm_families": [
                    int(r["warmFamilies"]) for r in recs
                ],
                "families_total": [
                    int(r["familiesTotal"]) for r in recs
                ],
                "result_cache": [
                    int(bool(r["resultCache"])) for r in recs
                ],
                "score": [float(r["score"]) for r in recs],
            }
        if table == "slos":
            from ..obs import serving_observatory as _so

            recs = _so.get_observatory().slo_rows()
            return {
                "tenant": [r["tenant"] for r in recs],
                "latency_target_s": [
                    float(r["latencyTargetS"]) for r in recs
                ],
                "error_budget": [
                    float(r["errorBudget"]) for r in recs
                ],
                "fast_window_s": [
                    float(r["fastWindowS"]) for r in recs
                ],
                "slow_window_s": [
                    float(r["slowWindowS"]) for r in recs
                ],
                "fast_burn_rate": [
                    float(r["fastBurnRate"]) for r in recs
                ],
                "slow_burn_rate": [
                    float(r["slowBurnRate"]) for r in recs
                ],
                "peak_fast_burn": [
                    float(r["peakFastBurn"]) for r in recs
                ],
                "violations_total": [
                    int(r["violationsTotal"]) for r in recs
                ],
                "observed_total": [
                    int(r["observedTotal"]) for r in recs
                ],
                "burn_events": [int(r["burnEvents"]) for r in recs],
                "p50_s": [float(r["p50S"]) for r in recs],
                "p95_s": [float(r["p95S"]) for r in recs],
                "p99_s": [float(r["p99S"]) for r in recs],
            }
        if table == "diagnoses":
            from ..obs import doctor as _doctor

            recs = _doctor.recent_diagnoses()
            return {
                "query_id": [d.get("queryId", "") for d in recs],
                "verdict": [d.get("verdict", "") for d in recs],
                "root_cause": [d.get("rootCause", "") for d in recs],
                "summary": [d.get("summary", "") for d in recs],
                "error_code": [d.get("errorCode", "") for d in recs],
                "event_ids": [
                    ",".join(str(i) for i in d.get("eventIds") or ())
                    for d in recs
                ],
                "findings": [
                    len(d.get("findings") or ()) for d in recs
                ],
                "wall_s": [float(d.get("wallS") or 0.0) for d in recs],
                "ts": [float(d.get("ts") or 0.0) for d in recs],
            }
        if table == "metrics":
            from ..utils.metrics import REGISTRY

            return REGISTRY.rows()
        raise KeyError(f"unknown system table: {table}")


class SystemMetadata(ConnectorMetadata):
    def __init__(self, source: _SystemSource):
        self.source = source

    def list_tables(self) -> List[str]:
        return list(SCHEMAS)

    def get_table_schema(self, table: str) -> TableSchema:
        return TableSchema(
            table,
            tuple(ColumnSchema(c, t) for c, t in SCHEMAS[table]),
        )

    def get_table_statistics(self, table: str) -> TableStatistics:
        return TableStatistics(100.0, {})


class SystemSplitManager(SplitManager):
    def get_splits(self, table: str, desired: int, constraint=None):
        return [Split(table, 0, 1)]


class SystemPageSource(PageSource):
    def __init__(self, source: _SystemSource, split: Split, columns):
        self.source = source
        self.split = split
        self.columns = list(columns)

    def pages(self):
        data = self.source.rows(self.split.table)
        schema = dict(SCHEMAS[self.split.table])
        cols = [
            column_from_pylist(schema[c], data[c]) for c in self.columns
        ]
        n = len(next(iter(data.values()))) if data else 0
        yield Page(cols, n, self.columns)

    def dictionaries(self) -> Dict[str, np.ndarray]:
        # per-column dictionaries ride on the Columns built in pages();
        # re-snapshotting here could diverge from that page
        return {}


class SystemPageSourceProvider(PageSourceProvider):
    def __init__(self, source: _SystemSource):
        self.source = source

    def create_page_source(self, split: Split, columns: Sequence[str]):
        return SystemPageSource(self.source, split, columns)


class SystemConnector(Connector):
    cacheable = False  # live engine state changes between queries
    coordinator_only = True  # snapshots THIS process; never runs on workers

    def __init__(self, name: str, session):
        self.name = name
        self.source = _SystemSource(session)

    def metadata(self):
        return SystemMetadata(self.source)

    def split_manager(self):
        return SystemSplitManager()

    def page_source_provider(self):
        return SystemPageSourceProvider(self.source)


class SystemConnectorFactory(ConnectorFactory):
    name = "system"

    def create(self, catalog_name: str, config: dict) -> SystemConnector:
        return SystemConnector(catalog_name, config["session"])
