"""Lakehouse connector: snapshot table format on the object store.

Reference parity: plugin/trino-iceberg reduced to its load-bearing core —
a table is (1) immutable data files, (2) per-snapshot manifests listing
them, and (3) ONE mutable object, the metadata pointer, replaced only by
compare-and-swap.  Everything ACID about Iceberg follows from that
split: writers prepare a whole new snapshot out of line (new data files,
new manifest, new metadata document) and then race a single CAS; the
loser journals ``SNAPSHOT_CONFLICT``, re-reads the winner's metadata and
retries with its already-written data files (they are immutable, so
re-use is safe), which is exactly Iceberg's optimistic-concurrency
commit loop.

Time travel: every committed snapshot stays addressable.  ``FOR VERSION
AS OF n`` / ``FOR TIMESTAMP AS OF t`` resolve to a snapshot id in the
analyzer (via :meth:`LakehouseMetadata.resolve_snapshot`) and pin the
scan by suffixing the table handle — ``"orders@3"`` — so splits, page
sources, statistics and ``data_version`` all key on the pinned snapshot
with no new plumbing.  ``data_version`` of an unpinned table IS its
current snapshot id, which makes the fragment result cache and the
stats sidecars invalidate per-snapshot for free.

Data files are numpy ``.npz`` objects (no parquet dependency): per
column the value array (2-D for wide decimals), optional validity, and
optional varchar dictionary.  The engine merges divergent per-split
dictionaries already (exec/local._load_one_scan), so each file keeps the
dictionary it was written with.

Chaos composes: the store underneath carries the ``objstore_*`` fault
sites, and the commit loop exposes ``lake_commit_crash`` — a kill-point
BETWEEN data-file write and metadata CAS, honored only in sacrificial
subprocess writers (``TRINO_TPU_CRASH_FAULTS=1``) — so crash tests can
prove torn commits are invisible: the pointer still names the old
metadata, the table reads at the prior snapshot, and the orphaned data
files are detectable by :meth:`LakehouseConnector.orphaned_files`.
"""
from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..fs import LocalObjectStore, ObjectStoreError
from ..obs import journal
from ..page import Column, Page, column_from_pylist
from ..spi import (
    ColumnSchema,
    ColumnStatistics,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSink,
    PageSinkProvider,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)
from ..utils.metrics import REGISTRY

# snapshot wire schema (system.runtime.snapshots detail + metadata JSON);
# linted by scripts/check_metric_names.py alongside the journal fields
SNAPSHOT_FIELDS = (
    "snapshotId",
    "parentId",
    "ts",
    "operation",
    "manifest",
    "dataFiles",
    "rows",
)

MAX_COMMIT_RETRIES = 10


def _split_handle(handle: str) -> Tuple[str, Optional[int]]:
    """``"orders@3"`` -> ("orders", 3); ``"orders"`` -> ("orders", None)."""
    if "@" in handle:
        name, _, snap = handle.rpartition("@")
        return name, int(snap)
    return handle, None


def _ptr_key(table: str) -> str:
    return f"{table}/metadata/ptr"


def _now_us() -> int:
    return time.time_ns() // 1000


class _TableState:
    """One consistent read of a table: the pointer bytes it was loaded
    from (the CAS expectation) plus the decoded metadata document."""

    def __init__(self, ptr: bytes, meta: dict):
        self.ptr = ptr
        self.meta = meta

    @property
    def current(self) -> int:
        return int(self.meta["currentSnapshotId"])

    def snapshot(self, snap_id: int) -> dict:
        for s in self.meta["snapshots"]:
            if int(s["snapshotId"]) == snap_id:
                return s
        raise ValueError(
            f"no snapshot {snap_id} for table {self.meta['table']} "
            f"(history: {[s['snapshotId'] for s in self.meta['snapshots']]})"
        )

    def schema(self) -> TableSchema:
        return TableSchema(
            self.meta["table"],
            tuple(
                ColumnSchema(n, T.parse_type(t))
                for n, t in self.meta["schema"]
            ),
        )


def _load_state(fs, table: str) -> _TableState:
    ptr = fs.read_file(_ptr_key(table))
    meta = json.loads(
        fs.read_file(f"{table}/metadata/{ptr.decode('ascii')}")
    )
    return _TableState(ptr, meta)


def _read_manifest(fs, table: str, snap: dict) -> List[dict]:
    return json.loads(
        fs.read_file(f"{table}/metadata/{snap['manifest']}")
    )["files"]


# -- data files: numpy .npz column serialization -----------------------
def _encode_data_file(schema: TableSchema, data: Dict[str, list]) -> bytes:
    """Python column values -> one .npz object (immutable data file)."""
    arrays: Dict[str, np.ndarray] = {}
    rows = 0
    for c in schema.columns:
        col = column_from_pylist(c.type, data[c.name])
        rows = len(data[c.name])
        arrays[f"v.{c.name}"] = np.asarray(col.values)
        if col.validity is not None:
            arrays[f"k.{c.name}"] = np.asarray(col.validity)
        if col.dictionary is not None:
            # <U serialization round-trips strings without pickle
            arrays[f"d.{c.name}"] = np.asarray(
                [str(x) for x in col.dictionary], dtype=str
            )
    arrays["rows"] = np.array([rows], dtype=np.int64)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _decode_data_file(schema: TableSchema, blob: bytes) -> Page:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        rows = int(z["rows"][0])
        cols = []
        for c in schema.columns:
            values = z[f"v.{c.name}"]
            validity = z[f"k.{c.name}"] if f"k.{c.name}" in z else None
            dictionary = None
            if f"d.{c.name}" in z:
                raw = z[f"d.{c.name}"]
                dictionary = np.empty(len(raw), dtype=object)
                for i, s in enumerate(raw):
                    dictionary[i] = str(s)
            cols.append(Column(c.type, values, validity, dictionary))
    return Page(cols, rows, list(schema.column_names()))


def _empty_page(schema: TableSchema) -> Page:
    cols = [column_from_pylist(c.type, []) for c in schema.columns]
    return Page(cols, 0, list(schema.column_names()))


class LakehouseMetadata(ConnectorMetadata):
    def __init__(self, conn: "LakehouseConnector"):
        self.conn = conn
        self.fs = conn.fs

    def list_tables(self) -> List[str]:
        out = []
        for e in self.fs.list_files():
            parts = e.path.split("/")
            if parts[-2:] == ["metadata", "ptr"]:
                out.append("/".join(parts[:-2]))
        return sorted(out)

    def _state(self, handle: str) -> Tuple[_TableState, Optional[int]]:
        name, pinned = _split_handle(handle)
        try:
            return _load_state(self.fs, name), pinned
        except ObjectStoreError:
            raise KeyError(f"table {name} does not exist") from None

    def get_table_schema(self, table: str) -> TableSchema:
        state, _ = self._state(table)
        return state.schema()

    def get_table_statistics(self, table: str) -> TableStatistics:
        state, pinned = self._state(table)
        snap = pinned if pinned is not None else state.current
        name, _ = _split_handle(table)
        try:
            raw = json.loads(
                self.fs.read_file(f"{name}/metadata/stats-{snap}.json")
            )
            return _stats_from_json(raw)
        except ObjectStoreError:
            return TableStatistics(
                float(state.snapshot(snap)["rows"]), {}
            )

    def store_table_statistics(
        self, table: str, stats: TableStatistics, data_version: int
    ) -> None:
        """ANALYZE sidecar keyed BY SNAPSHOT (data_version == snapshot
        id here): stats written at snapshot N are served only for reads
        pinned at N or while N is still current — a later write moves
        the pointer and the stale sidecar becomes unaddressable."""
        name, _ = _split_handle(table)
        self.fs.write_file(
            f"{name}/metadata/stats-{int(data_version)}.json",
            json.dumps(_stats_to_json(stats)).encode(),
        )

    # -- time travel ----------------------------------------------------
    def resolve_snapshot(self, table: str, kind: str, value) -> int:
        """Resolve FOR VERSION|TIMESTAMP AS OF to a snapshot id; the
        analyzer turns ValueError into a SemanticError at the query."""
        state, _ = self._state(table)
        REGISTRY.counter(
            "trino_tpu_lake_time_travel_total",
            "Time-travel clauses resolved to pinned snapshots",
        ).inc(kind=kind)
        if kind == "version":
            snap = int(value)
            state.snapshot(snap)  # raises ValueError if unknown
            return snap
        # timestamp: latest snapshot committed at or before the bound
        bound = _timestamp_us(value)
        best = None
        for s in state.meta["snapshots"]:
            if int(s["ts"]) <= bound and (
                best is None or int(s["snapshotId"]) > best
            ):
                best = int(s["snapshotId"])
        if best is None:
            raise ValueError(
                f"no snapshot of {state.meta['table']} at or before "
                f"timestamp {value!r} (oldest is "
                f"ts={state.meta['snapshots'][0]['ts']})"
            )
        return best

    # -- DDL -------------------------------------------------------------
    def create_table(self, schema: TableSchema) -> None:
        table = schema.name
        # same per-attempt token as the commit loop: two racing CREATEs
        # must not overwrite each other's snapshot-0 documents (the CAS
        # on the pointer picks the winner; the loser's files are inert)
        token = uuid.uuid4().hex[:8]
        manifest = f"manifest-0-{token}.json"
        self.fs.write_file(
            f"{table}/metadata/{manifest}",
            json.dumps({"snapshotId": 0, "files": []}).encode(),
        )
        meta = {
            "formatVersion": 1,
            "table": table,
            "schema": [[c.name, str(c.type)] for c in schema.columns],
            "currentSnapshotId": 0,
            "snapshots": [
                {
                    "snapshotId": 0,
                    "parentId": None,
                    "ts": _now_us(),
                    "operation": "create",
                    "manifest": manifest,
                    "dataFiles": 0,
                    "rows": 0,
                }
            ],
        }
        meta_name = f"v0-{token}.json"
        self.fs.write_file(
            f"{table}/metadata/{meta_name}", json.dumps(meta).encode()
        )
        if not self.fs.compare_and_swap(
            _ptr_key(table), None, meta_name.encode()
        ):
            raise ValueError(f"table {table} already exists")

    def drop_table(self, table: str) -> None:
        name, _ = _split_handle(table)
        entries = self.fs.list_files(name)
        if not any(e.path == _ptr_key(name) for e in entries):
            raise KeyError(f"table {name} does not exist")
        for e in entries:
            self.fs.delete_file(e.path)


def _stats_to_json(stats: TableStatistics) -> dict:
    return {
        "rowCount": stats.row_count,
        "columns": {
            name: dataclasses.asdict(cs)
            for name, cs in stats.columns.items()
        },
    }


def _stats_from_json(raw: dict) -> TableStatistics:
    cols = {}
    for name, cs in raw.get("columns", {}).items():
        hist = cs.get("histogram")
        cols[name] = ColumnStatistics(
            distinct_count=cs.get("distinct_count"),
            null_fraction=cs.get("null_fraction", 0.0),
            min_value=cs.get("min_value"),
            max_value=cs.get("max_value"),
            histogram=(
                tuple(tuple(b) for b in hist) if hist else None
            ),
        )
    return TableStatistics(float(raw["rowCount"]), cols)


def _timestamp_us(value) -> int:
    """FOR TIMESTAMP AS OF operand -> epoch microseconds.  Accepts the
    engine's timestamp representation (int us) or a literal string."""
    if isinstance(value, (int, np.integer)):
        return int(value)
    return int(
        (
            np.datetime64(str(value).strip().replace(" ", "T"), "us")
            - np.datetime64("1970-01-01", "us")
        ).astype(np.int64)
    )


class LakehouseSplitManager(SplitManager):
    def __init__(self, conn: "LakehouseConnector"):
        self.conn = conn

    def get_splits(
        self, table: str, desired: int, constraint=None
    ) -> List[Split]:
        name, pinned = _split_handle(table)
        state = _load_state(self.conn.fs, name)
        snap = state.snapshot(
            pinned if pinned is not None else state.current
        )
        files = _read_manifest(self.conn.fs, name, snap)
        schema_wire = state.meta["schema"]
        if not files:
            return [
                Split(table, 0, 1, {"path": None, "schema": schema_wire})
            ]
        return [
            Split(
                table, i, len(files),
                {
                    "path": f["path"],
                    "rows": f["rows"],
                    "schema": schema_wire,
                },
            )
            for i, f in enumerate(files)
        ]


class LakehousePageSource(PageSource):
    """One data file per split; dictionaries are per-file and the engine
    remaps codes when merging splits."""

    def __init__(self, conn: "LakehouseConnector", split: Split,
                 columns: Sequence[str]):
        self.conn = conn
        self.split = split
        self.columns = list(columns)
        self._dicts: Dict[str, np.ndarray] = {}

    def pages(self):
        schema = TableSchema(
            _split_handle(self.split.table)[0],
            tuple(
                ColumnSchema(n, T.parse_type(t))
                for n, t in self.split.info["schema"]
            ),
        )
        path = self.split.info.get("path")
        if path is None:
            page = _empty_page(schema)
        else:
            page = _decode_data_file(
                schema, self.conn.fs.read_file(path)
            )
        cols = [page.by_name(c) for c in self.columns]
        for c, col in zip(self.columns, cols):
            if col.dictionary is not None:
                self._dicts[c] = col.dictionary
        yield Page(cols, page.count, self.columns)

    def dictionaries(self) -> Dict[str, np.ndarray]:
        return dict(self._dicts)


class LakehousePageSourceProvider(PageSourceProvider):
    def __init__(self, conn: "LakehouseConnector"):
        self.conn = conn

    def create_page_source(self, split: Split, columns):
        return LakehousePageSource(self.conn, split, columns)


class LakehousePageSink(PageSink):
    """The optimistic-concurrency commit loop (Iceberg
    SnapshotProducer.commit analog).  Data files written once, metadata
    raced via CAS, loser re-reads and retries with the same files."""

    def __init__(self, conn: "LakehouseConnector", table: str,
                 columns, overwrite: bool):
        self.conn = conn
        self.table = table
        self.columns = list(columns)
        self.overwrite = overwrite
        self.buffered: List[list] = [[] for _ in self.columns]
        self.rows = 0

    def append(self, page: Page) -> None:
        for i, name in enumerate(self.columns):
            self.buffered[i].extend(
                page.by_name(name).to_python(page.count)
            )
        self.rows += page.count

    def finish(self) -> int:
        fs = self.conn.fs
        t0 = time.perf_counter()
        state = _load_state(fs, self.table)
        schema = state.schema()

        # 1. write the immutable data file ONCE, out of line — CAS
        #    losers reuse it across retries (immutability makes reuse
        #    safe; a crashed writer just leaves it orphaned)
        new_file: Optional[dict] = None
        if self.rows:
            data: Dict[str, list] = {}
            for c in schema.columns:
                try:
                    idx = self.columns.index(c.name)
                    data[c.name] = self.buffered[idx]
                except ValueError:
                    data[c.name] = [None] * self.rows
            blob = _encode_data_file(schema, data)
            path = (
                f"{self.table}/data/{uuid.uuid4().hex}.npz"
            )
            fs.write_file(path, blob)
            REGISTRY.counter(
                "trino_tpu_lake_written_bytes",
                "Data-file bytes committed to lakehouse tables",
            ).inc(len(blob))
            new_file = {"path": path, "rows": self.rows}

        op = "overwrite" if self.overwrite else "append"
        for attempt in range(MAX_COMMIT_RETRIES):
            snap_id = state.current + 1
            self.conn.maybe_crash(f"{self.table}:{snap_id}")

            # 2. prepare the new snapshot's manifest + metadata document.
            #    Both filenames carry a per-attempt token (Iceberg's
            #    <version>-<uuid>.metadata.json): two writers racing to
            #    the same snapshot id must never collide on a filename,
            #    or the CAS loser's overwrite would replace the document
            #    the winner's pointer references
            token = uuid.uuid4().hex[:8]
            base_files = (
                []
                if self.overwrite
                else _read_manifest(
                    fs, self.table, state.snapshot(state.current)
                )
            )
            files = base_files + ([new_file] if new_file else [])
            manifest = f"manifest-{snap_id}-{token}.json"
            fs.write_file(
                f"{self.table}/metadata/{manifest}",
                json.dumps(
                    {"snapshotId": snap_id, "files": files}
                ).encode(),
            )
            meta = dict(state.meta)
            meta["currentSnapshotId"] = snap_id
            meta["snapshots"] = list(state.meta["snapshots"]) + [
                {
                    "snapshotId": snap_id,
                    "parentId": state.current,
                    "ts": _now_us(),
                    "operation": op,
                    "manifest": manifest,
                    "dataFiles": len(files),
                    "rows": sum(int(f["rows"]) for f in files),
                }
            ]
            meta_name = f"v{snap_id}-{token}.json"
            fs.write_file(
                f"{self.table}/metadata/{meta_name}",
                json.dumps(meta).encode(),
            )

            # 3. race the pointer
            if fs.compare_and_swap(
                _ptr_key(self.table), state.ptr,
                meta_name.encode(),
            ):
                REGISTRY.counter(
                    "trino_tpu_lake_commits_total",
                    "Lakehouse snapshot commits by operation",
                ).inc(op=op)
                REGISTRY.histogram(
                    "trino_tpu_lake_commit_seconds",
                    "Wall seconds per lakehouse commit (incl. retries)",
                ).observe(time.perf_counter() - t0)
                return self.rows

            # lost the race: journal, re-read the winner, retry with the
            # SAME data file (it is immutable — only metadata re-derives)
            state = _load_state(fs, self.table)
            REGISTRY.counter(
                "trino_tpu_lake_conflicts_total",
                "Lakehouse commit CAS losses (retried)",
            ).inc(op=op)
            journal.emit(
                journal.SNAPSHOT_CONFLICT,
                severity=journal.WARN,
                table=self.table,
                attempted=snap_id,
                winner=state.current,
                attempt=attempt + 1,
            )
        raise ObjectStoreError(
            f"commit to {self.table} lost the metadata CAS "
            f"{MAX_COMMIT_RETRIES} times; giving up"
        )


class LakehousePageSinkProvider(PageSinkProvider):
    def __init__(self, conn: "LakehouseConnector"):
        self.conn = conn

    def create_sink(self, table: str, columns, overwrite: bool = False):
        name, pinned = _split_handle(table)
        if pinned is not None:
            raise ValueError(
                f"cannot write to a pinned snapshot: {table}"
            )
        return LakehousePageSink(self.conn, name, columns, overwrite)


class LakehouseConnector(Connector):
    cacheable = True  # data_version == snapshot id: per-snapshot keys

    def __init__(self, name: str, fs: LocalObjectStore, injector=None):
        self.name = name
        self.fs = fs
        self.injector = injector

    def maybe_crash(self, key: str) -> None:
        """lake_commit_crash kill-point: only sacrificial subprocess
        writers honor it (see utils/faults.SITES) — firing it in-process
        would take the whole test runner down."""
        inj = self.injector
        if (
            inj is not None
            and os.environ.get("TRINO_TPU_CRASH_FAULTS") == "1"
            and inj.fires("lake_commit_crash", key)
        ):
            os._exit(137)

    # -- cache-invalidation SPI -----------------------------------------
    def data_version(self, table: Optional[str] = None) -> int:
        if table is not None:
            name, pinned = _split_handle(table)
            if pinned is not None:
                return pinned  # pinned scans never invalidate
            try:
                return _load_state(self.fs, name).current
            except ObjectStoreError:
                return 0
        # whole-catalog: content-derived digest over (table, snapshot)
        # pairs — process-stable, moves on any table's commit/drop
        h = hashlib.blake2b(digest_size=8)
        for t in self.metadata().list_tables():
            try:
                h.update(
                    f"{t}={_load_state(self.fs, t).current};".encode()
                )
            except ObjectStoreError:
                continue
        return int.from_bytes(h.digest(), "big") & (2**62 - 1)

    # -- maintenance / introspection ------------------------------------
    def snapshots_rows(self) -> List[tuple]:
        """system.runtime.snapshots feed: one row per committed snapshot
        of every table in this catalog."""
        out = []
        md = self.metadata()
        for t in md.list_tables():
            state = _load_state(self.fs, t)
            for s in state.meta["snapshots"]:
                out.append(
                    (
                        t,
                        int(s["snapshotId"]),
                        -1 if s["parentId"] is None
                        else int(s["parentId"]),
                        str(s["operation"]),
                        int(s["dataFiles"]),
                        int(s["rows"]),
                        int(s["snapshotId"]) == state.current,
                        int(s["ts"]),
                    )
                )
        return out

    def orphaned_files(self, table: str) -> List[str]:
        """Data files not referenced by any committed snapshot — what a
        crashed or still-in-flight writer leaves behind (Iceberg's
        remove_orphan_files procedure reduced to detection)."""
        name, _ = _split_handle(table)
        state = _load_state(self.fs, name)
        referenced = set()
        for s in state.meta["snapshots"]:
            for f in _read_manifest(self.fs, name, s):
                referenced.add(f["path"])
        return sorted(
            e.path
            for e in self.fs.list_files(f"{name}/data")
            if e.path not in referenced
        )

    def expire_snapshots(self, table: str, keep: int = 1) -> dict:
        """Prune snapshot history down to the newest ``keep`` snapshots
        (the current one always survives), reclaiming manifests and any
        data files only expired snapshots referenced — Iceberg's
        ``expire_snapshots`` procedure.

        The metadata change rides the SAME compare-and-swap commit
        protocol as writers: prepare a token-named metadata document
        with the pruned history, race the pointer, and on a lost CAS
        re-read the winner and retry — so maintenance is safe to run
        concurrently with appends.  Files are deleted only AFTER the CAS
        lands: until then every snapshot is still reachable, and the
        immutable loser documents are mere orphan metadata."""
        name, pinned = _split_handle(table)
        if pinned is not None:
            raise ValueError(
                f"cannot run maintenance on a pinned snapshot: {table}"
            )
        keep = max(int(keep), 1)
        fs = self.fs
        t0 = time.perf_counter()
        for attempt in range(MAX_COMMIT_RETRIES):
            state = _load_state(fs, name)
            snaps = list(state.meta["snapshots"])
            kept = snaps[-keep:]
            if not any(
                int(s["snapshotId"]) == state.current for s in kept
            ):
                kept = [
                    s for s in snaps
                    if int(s["snapshotId"]) == state.current
                ] + kept
            dropped = [s for s in snaps if s not in kept]
            if not dropped:
                return {
                    "table": name, "expiredSnapshots": 0,
                    "removedFiles": 0,
                    "currentSnapshotId": state.current,
                }
            kept_refs = {
                f["path"]
                for s in kept
                for f in _read_manifest(fs, name, s)
            }
            dropped_refs = {
                f["path"]
                for s in dropped
                for f in _read_manifest(fs, name, s)
            }
            token = uuid.uuid4().hex[:8]
            meta = dict(state.meta)
            meta["snapshots"] = kept
            meta_name = f"v{state.current}-{token}.json"
            fs.write_file(
                f"{name}/metadata/{meta_name}", json.dumps(meta).encode()
            )
            if fs.compare_and_swap(
                _ptr_key(name), state.ptr, meta_name.encode()
            ):
                removed = 0
                for s in dropped:
                    try:
                        fs.delete_file(
                            f"{name}/metadata/{s['manifest']}"
                        )
                    except ObjectStoreError:
                        pass
                for p in sorted(dropped_refs - kept_refs):
                    try:
                        fs.delete_file(p)
                        removed += 1
                    except ObjectStoreError:
                        pass
                REGISTRY.counter(
                    "trino_tpu_lake_commits_total",
                    "Lakehouse snapshot commits by operation",
                ).inc(op="expire_snapshots")
                REGISTRY.counter(
                    "trino_tpu_lake_expired_snapshots_total",
                    "Snapshots pruned by expire_snapshots",
                ).inc(len(dropped))
                REGISTRY.histogram(
                    "trino_tpu_lake_commit_seconds",
                    "Wall seconds per lakehouse commit (incl. retries)",
                ).observe(time.perf_counter() - t0)
                journal.emit(
                    journal.SNAPSHOT_EXPIRED,
                    severity=journal.INFO,
                    table=name,
                    expired=len(dropped),
                    removedFiles=removed,
                    currentSnapshotId=state.current,
                )
                return {
                    "table": name,
                    "expiredSnapshots": len(dropped),
                    "removedFiles": removed,
                    "currentSnapshotId": state.current,
                }
            REGISTRY.counter(
                "trino_tpu_lake_conflicts_total",
                "Lakehouse commit CAS losses (retried)",
            ).inc(op="expire_snapshots")
            journal.emit(
                journal.SNAPSHOT_CONFLICT,
                severity=journal.WARN,
                table=name,
                attempted=state.current,
                winner=_load_state(fs, name).current,
                attempt=attempt + 1,
            )
        raise ObjectStoreError(
            f"expire_snapshots on {name} lost the metadata CAS "
            f"{MAX_COMMIT_RETRIES} times; giving up"
        )

    def remove_orphan_files(
        self, table: str, older_than_s: float = 0.0
    ) -> dict:
        """Delete data files no committed snapshot references — what a
        crashed writer (or a CAS loser that never retried) leaves
        behind.  ``older_than_s`` is the in-flight-writer grace: a live
        writer's data file exists BEFORE its commit CAS lands, so
        production callers pass an age floor (Iceberg defaults to 3
        days); tests pass 0.

        Validation rides the commit protocol: after computing the
        orphan set the pointer is re-read, and if a concurrent commit
        moved it the scan restarts — a file that just became referenced
        must not be swept."""
        name, _ = _split_handle(table)
        fs = self.fs
        now_ns = time.time_ns()
        for attempt in range(MAX_COMMIT_RETRIES):
            state = _load_state(fs, name)
            referenced = set()
            for s in state.meta["snapshots"]:
                for f in _read_manifest(fs, name, s):
                    referenced.add(f["path"])
            candidates = [
                e for e in fs.list_files(f"{name}/data")
                if e.path not in referenced
                and (now_ns - e.mtime_ns) >= older_than_s * 1e9
            ]
            # pointer unchanged => no commit raced the scan; a moved
            # pointer may have promoted a candidate to referenced
            if fs.read_file(_ptr_key(name)) != state.ptr:
                continue
            removed = 0
            freed = 0
            for e in candidates:
                try:
                    fs.delete_file(e.path)
                    removed += 1
                    freed += int(e.size)
                except ObjectStoreError:
                    pass
            REGISTRY.counter(
                "trino_tpu_lake_orphans_removed_total",
                "Orphan data files reclaimed by remove_orphan_files",
            ).inc(removed)
            journal.emit(
                journal.ORPHANS_REMOVED,
                severity=journal.INFO,
                table=name,
                removedFiles=removed,
                freedBytes=freed,
            )
            return {
                "table": name, "removedFiles": removed,
                "freedBytes": freed,
            }
        raise ObjectStoreError(
            f"remove_orphan_files on {name} kept racing commits "
            f"{MAX_COMMIT_RETRIES} times; giving up"
        )

    def metadata(self) -> LakehouseMetadata:
        return LakehouseMetadata(self)

    def split_manager(self) -> LakehouseSplitManager:
        return LakehouseSplitManager(self)

    def page_source_provider(self) -> LakehousePageSourceProvider:
        return LakehousePageSourceProvider(self)

    def page_sink_provider(self) -> LakehousePageSinkProvider:
        return LakehousePageSinkProvider(self)


class LakehouseConnectorFactory(ConnectorFactory):
    name = "lakehouse"

    def create(self, catalog_name: str, config: dict) -> LakehouseConnector:
        root = config.get("lake.warehouse-dir")
        if not root:
            raise ValueError(
                "lakehouse catalog requires lake.warehouse-dir"
            )
        injector = None
        spec = config.get("lake.fault-injection")
        if spec:
            from ..utils.faults import FaultInjector

            injector = FaultInjector.from_spec(spec)
        fs = LocalObjectStore(root, injector=injector)
        return LakehouseConnector(catalog_name, fs, injector=injector)
