"""Blackhole connector: null source for perf tests.

Reference parity: plugin/trino-blackhole — tables produce a configurable
number of synthetic rows (and swallow writes); used to benchmark operator
paths without real IO.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .. import types as T
from ..page import Column, Page
from ..spi import (
    ColumnSchema,
    Connector,
    ConnectorFactory,
    ConnectorMetadata,
    PageSource,
    PageSourceProvider,
    Split,
    SplitManager,
    TableSchema,
    TableStatistics,
)


class BlackholeConnector(Connector):
    def __init__(self, name: str, config: dict):
        self.name = name
        self.rows = int(config.get("blackhole.rows-per-table", 1000))
        self._schemas: Dict[str, TableSchema] = {
            "numbers": TableSchema(
                "numbers",
                (
                    ColumnSchema("n", T.BIGINT),
                    ColumnSchema("v", T.DOUBLE),
                ),
            )
        }

    def metadata(self):
        conn = self

        class MD(ConnectorMetadata):
            def list_tables(self):
                return list(conn._schemas)

            def get_table_schema(self, table):
                return conn._schemas[table]

            def get_table_statistics(self, table):
                return TableStatistics(float(conn.rows), {})

        return MD()

    def split_manager(self):
        conn = self

        class SM(SplitManager):
            def get_splits(self, table, desired, constraint=None):
                k = max(1, desired)
                return [Split(table, i, k) for i in range(k)]

        return SM()

    def page_source_provider(self):
        conn = self

        class PSP(PageSourceProvider):
            def create_page_source(self, split, columns):
                return _Source(conn, split, columns)

        return PSP()


class _Source(PageSource):
    def __init__(self, conn: BlackholeConnector, split: Split, columns):
        self.conn = conn
        self.split = split
        self.columns = list(columns)

    def pages(self):
        lo = self.conn.rows * self.split.ordinal // self.split.total
        hi = self.conn.rows * (self.split.ordinal + 1) // self.split.total
        n = hi - lo
        idx = np.arange(lo, hi, dtype=np.int64)
        cols = []
        for c in self.columns:
            if c == "n":
                cols.append(Column(T.BIGINT, idx))
            else:
                cols.append(Column(T.DOUBLE, (idx * 0.5).astype(np.float64)))
        yield Page(cols, n, self.columns)


class BlackholeConnectorFactory(ConnectorFactory):
    name = "blackhole"

    def create(self, catalog_name: str, config: dict) -> BlackholeConnector:
        return BlackholeConnector(catalog_name, config)
