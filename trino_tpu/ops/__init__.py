"""Kernel building blocks (aggregation, join, sort, window, ...).

Shared byte-accounting helpers live here: the bandwidth ledger
(``trino_tpu/obs/bandwidth.py``) charges every supervised dispatch with
the bytes its operator tree touches, and the lane pytrees it must walk
are the same nested dict/tuple shapes the ops modules produce.
"""
from __future__ import annotations


def tree_nbytes(tree) -> int:
    """Total ``nbytes`` across every array leaf of a lane pytree.

    Accepts the nested dict/tuple/list shapes dispatches produce (output
    lane maps, ``(values, validity)`` pairs, check-scalar tuples); leaves
    without ``nbytes`` (python scalars, None validity) count as zero.
    """
    total = 0
    stack = [tree]
    while stack:
        node = stack.pop()
        if node is None:
            continue
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (tuple, list)):
            stack.extend(node)
        else:
            nb = getattr(node, "nbytes", None)
            if nb is not None:
                total += int(nb)
    return total
