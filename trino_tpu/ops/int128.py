"""Emulated 128-bit integer arithmetic on int64 lanes.

Reference parity: spi/type/Int128Math.java — the reference's decimal engine
computes rescales, multiplications and divisions in 128-bit two-limb
arithmetic so decimal(38) intermediates never overflow.  TPUs have no
native int128, so the limbs are uint64 jax arrays: products split into
32-bit halves (four partial products), and 128/64 division runs the
classic shift-subtract loop (128 fixed iterations — a static-shape
`lax.fori_loop` the compiler unrolls onto the VPU; ~128 cheap ops/lane).

Values stay *stored* as scaled int64 (decimal ≤ 18 digits); these kernels
protect the transient wide intermediates (e.g. Q14's
`100.00 * sum(..) / sum(..)`, whose numerator rescale exceeds 2^63).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# python ints, NOT jnp scalars: module-level jnp constants are captured
# as hidden const ARGUMENTS of every jitted program using them, and the
# axon tunnel corrupts re-dispatch of such programs (INVALID_ARGUMENT on
# every warm run once a sibling program exists — measured, 2026-07-30);
# plain ints fold into HLO literals
_MASK32 = 0xFFFFFFFF
_U1 = 1


def umul128(a: jnp.ndarray, b: jnp.ndarray):
    """Unsigned 64x64 -> 128-bit product as (hi, lo) uint64 limbs."""
    a = a.astype(jnp.uint64)
    b = b.astype(jnp.uint64)
    a0, a1 = a & _MASK32, a >> jnp.uint64(32)
    b0, b1 = b & _MASK32, b >> jnp.uint64(32)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> jnp.uint64(32)) + (p01 & _MASK32) + (p10 & _MASK32)
    lo = (p00 & _MASK32) | ((mid & _MASK32) << jnp.uint64(32))
    hi = (
        p11
        + (p01 >> jnp.uint64(32))
        + (p10 >> jnp.uint64(32))
        + (mid >> jnp.uint64(32))
    )
    return hi, lo


def udiv128_64(hi: jnp.ndarray, lo: jnp.ndarray, d: jnp.ndarray):
    """(hi:lo) / d -> (quotient low 64 bits, remainder).

    Requires d >= 1 and d < 2^63 (scaled-decimal divisors always are).
    Quotients that exceed 64 bits return their low limb — callers bound
    result precision so exact results always fit."""
    d = d.astype(jnp.uint64)

    def body(i, st):
        rem, q = st
        bit_index = jnp.uint64(127) - jnp.uint64(i)
        word = jnp.where(bit_index >= jnp.uint64(64), hi, lo)
        sh = jnp.where(
            bit_index >= jnp.uint64(64),
            bit_index - jnp.uint64(64),
            bit_index,
        )
        bit = (word >> sh) & _U1
        rem = (rem << _U1) | bit
        ge = rem >= d
        rem = jnp.where(ge, rem - d, rem)
        q = (q << _U1) | ge.astype(jnp.uint64)
        return rem, q

    rem0 = jnp.zeros_like(d)
    q0 = jnp.zeros_like(d)
    rem, q = jax.lax.fori_loop(0, 128, body, (rem0, q0))
    return q, rem


def udiv128_128(hi, lo, dhi_c: int, dlo_c: int):
    """(hi:lo) / compile-time-constant 128-bit divisor -> 64-bit quotient
    + 128-bit remainder.  Used for /10^k with k up to 38 (10^38 < 2^127).
    Restoring division over two limbs; quotients are bounded by callers'
    precision rules to fit one limb."""
    dhi = jnp.uint64(dhi_c)
    dlo = jnp.uint64(dlo_c)

    def body(i, st):
        rhi, rlo, q = st
        bit_index = jnp.uint64(127) - jnp.uint64(i)
        word = jnp.where(bit_index >= jnp.uint64(64), hi, lo)
        sh = jnp.where(
            bit_index >= jnp.uint64(64),
            bit_index - jnp.uint64(64),
            bit_index,
        )
        bit = (word >> sh) & _U1
        # rem = rem << 1 | bit  (128-bit)
        rhi = (rhi << _U1) | (rlo >> jnp.uint64(63))
        rlo = (rlo << _U1) | bit
        ge = (rhi > dhi) | ((rhi == dhi) & (rlo >= dlo))
        borrow = (rlo < dlo).astype(jnp.uint64)
        rhi = jnp.where(ge, rhi - dhi - borrow, rhi)
        rlo = jnp.where(ge, rlo - dlo, rlo)
        q = (q << _U1) | ge.astype(jnp.uint64)
        return rhi, rlo, q

    z = jnp.zeros_like(lo)
    rhi, rlo, q = jax.lax.fori_loop(0, 128, body, (z, z, z))
    return q, rhi, rlo


def _div_const_round(hi, lo, const: int):
    """(hi:lo) / const with round-half-away, const any positive int
    < 2^127 known at trace time; returns uint64 quotient."""
    if const < (1 << 62):
        d = jnp.full_like(lo, const)
        q, rem = udiv128_64(hi, lo, d)
        return q + (jnp.uint64(2) * rem >= d).astype(jnp.uint64)
    q, rhi, rlo = udiv128_128(lo=lo, hi=hi, dhi_c=const >> 64,
                              dlo_c=const & ((1 << 64) - 1))
    # round half away: 2*rem >= const, in 128-bit
    r2hi = (rhi << _U1) | (rlo >> jnp.uint64(63))
    r2lo = rlo << _U1
    dhi = jnp.uint64(const >> 64)
    dlo = jnp.uint64(const & ((1 << 64) - 1))
    up = (r2hi > dhi) | ((r2hi == dhi) & (r2lo >= dlo))
    return q + up.astype(jnp.uint64)


def mul_shift_div_round(
    l: jnp.ndarray, mul: int, den: jnp.ndarray
) -> jnp.ndarray:
    """round_half_away((l * mul) / den) for signed int64 lanes with a
    128-bit intermediate product (DecimalOperators.divide* analog).
    `mul` is a trace-time power of ten; `den` a scaled int64 lane."""
    sign = jnp.sign(l) * jnp.sign(den)
    al = jnp.abs(l).astype(jnp.uint64)
    ad = jnp.abs(jnp.where(den == 0, 1, den)).astype(jnp.uint64)
    if mul < (1 << 64):
        hi, lo = umul128(al, jnp.uint64(mul))
    else:
        # l * 10^k with 10^k >= 2^64: split the constant into
        # c = c1 * 2^64 + c0; hi limb gains al*c1 (low limb of it)
        c1, c0 = mul >> 64, mul & ((1 << 64) - 1)
        hi, lo = umul128(al, jnp.uint64(c0))
        hi = hi + al * jnp.uint64(c1)
    q, rem = udiv128_64(hi, lo, ad)
    q = q + (jnp.uint64(2) * rem >= ad).astype(jnp.uint64)
    return sign * q.astype(jnp.int64)


def mul_rescale_round(
    l: jnp.ndarray, r: jnp.ndarray, down: int
) -> jnp.ndarray:
    """round_half_away((l * r) / 10^down) with a 128-bit product
    (DecimalOperators.multiply + Decimals.rescale fused)."""
    sign = jnp.sign(l) * jnp.sign(r)
    hi, lo = umul128(jnp.abs(l).astype(jnp.uint64), jnp.abs(r).astype(jnp.uint64))
    if down <= 0:
        return sign * lo.astype(jnp.int64)
    q = _div_const_round(hi, lo, 10**down)
    return sign * q.astype(jnp.int64)
