"""Row pattern matching for MATCH_RECOGNIZE.

Reference parity: core/trino-main/.../operator/window/matcher/ (the NFA
Matcher over an IrRowPattern) + pattern semantics from
sql/analyzer/PatternRecognitionAnalysis.  Here a backtracking matcher runs
host-side per partition (the reference is also a row-at-a-time automaton);
DEFINE/MEASURES expressions are evaluated by the shared IR interpreter
(expr/arrays.eval_ir) with a navigation resolver for PREV/NEXT/FIRST/LAST/
CLASSIFIER/MATCH_NUMBER.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..expr import ir
from ..expr.arrays import eval_ir

NAV_FUNCS = (
    "__mr_prev__", "__mr_next__", "__mr_first__", "__mr_last__",
    "__mr_classifier__", "__mr_match_number__",
)


class MatchContext:
    """One partition's rows + the in-flight match state."""

    def __init__(self, columns: Dict[str, list], nrows: int):
        self.columns = columns
        self.nrows = nrows
        self.match_number = 0
        # current (possibly tentative) mapping: list of (row, var)
        self.bindings: List[Tuple[int, str]] = []
        self.current_row = 0

    # -- navigation ---------------------------------------------------
    def value(self, col: str, row: int):
        if 0 <= row < self.nrows:
            return self.columns[col][row]
        return None

    def rows_of(self, var: str) -> List[int]:
        if var == "":
            return [r for r, _ in self.bindings]
        return [r for r, v in self.bindings if v == var]

    def special(self, e: ir.Expr, env):
        """eval_ir `special` hook: claims navigation calls."""
        if not isinstance(e, ir.Call) or e.name not in NAV_FUNCS:
            return False, None
        if e.name == "__mr_classifier__":
            for r, v in reversed(self.bindings):
                if r == self.current_row:
                    return True, v.upper()
            return True, None
        if e.name == "__mr_match_number__":
            return True, self.match_number
        colref = e.args[0]
        assert isinstance(colref, ir.ColumnRef)
        if e.name in ("__mr_prev__", "__mr_next__"):
            n = int(e.args[1].value)
            off = -n if e.name == "__mr_prev__" else n
            return True, self.value(colref.name, self.current_row + off)
        var = str(e.args[1].value)
        rows = self.rows_of(var)
        if not rows:
            return True, None
        row = rows[0] if e.name == "__mr_first__" else rows[-1]
        return True, self.value(colref.name, row)

    def eval(self, expr: ir.Expr, row: int):
        self.current_row = row
        env = {c: vals[row] for c, vals in self.columns.items()}
        return eval_ir(expr, env, self.special)


def _match_term(term, pos: int, ctx: MatchContext, defines, out_len):
    """Backtracking generator of end positions; ctx.bindings holds the
    mapping for the branch currently being explored."""
    if term.kind == "var":
        reps = _quantifier_range(term.quantifier)
        yield from _match_var(term.var, reps, term.greedy, pos, ctx, defines)
        return
    if term.kind == "alt":
        for branch in term.items:
            yield from _match_term(branch, pos, ctx, defines, out_len)
        return
    # group: sequence with optional quantifier over the whole group
    reps = _quantifier_range(term.quantifier)
    yield from _match_group(term.items, reps, term.greedy, pos, ctx, defines)


def _quantifier_range(q: str) -> Tuple[int, Optional[int]]:
    return {"": (1, 1), "?": (0, 1), "*": (0, None), "+": (1, None)}[q]


def _match_var(var, reps, greedy, pos, ctx, defines):
    lo, hi = reps

    def extend(count, p):
        if count >= lo:
            if greedy:
                if hi is None or count < hi:
                    yield from try_one(count, p)
                yield p
            else:
                yield p
                if hi is None or count < hi:
                    yield from try_one(count, p)
        else:
            yield from try_one(count, p)

    def try_one(count, p):
        if p >= ctx.nrows:
            return
        cond = defines.get(var)
        ctx.bindings.append((p, var))
        ok = True
        if cond is not None:
            ok = ctx.eval(cond, p) is True
        if ok:
            yield from extend(count + 1, p + 1)
        ctx.bindings.pop()

    yield from extend(0, pos)


def _match_group(items, reps, greedy, pos, ctx, defines):
    lo, hi = reps

    def seq(idx, p):
        if idx == len(items):
            yield p
            return
        for end in _match_term(items[idx], p, ctx, defines, None):
            yield from seq(idx + 1, end)

    def extend(count, p):
        if count >= lo:
            if greedy:
                if hi is None or count < hi:
                    yield from try_one(count, p)
                yield p
            else:
                yield p
                if hi is None or count < hi:
                    yield from try_one(count, p)
        else:
            yield from try_one(count, p)

    def try_one(count, p):
        mark = len(ctx.bindings)
        for end in seq(0, p):
            if end == p and count >= lo:
                continue  # empty group iteration: no progress
            yield from extend(count + 1, end)
        del ctx.bindings[mark:]

    yield from extend(0, pos)


def find_matches(
    columns: Dict[str, list],
    nrows: int,
    pattern,
    defines: Dict[str, ir.Expr],
    measures: Sequence[Tuple[str, ir.Expr]],
    after_match: str = "past_last_row",
    all_rows: bool = False,
) -> List[dict]:
    """Run the automaton over one partition.

    ONE ROW PER MATCH (all_rows=False): one dict per match, measures
    evaluated FINAL on the last mapped row.  ALL ROWS PER MATCH: one dict
    per MAPPED ROW with measures evaluated at that row (RUNNING semantics)
    plus '__row__' = the partition-relative source row index."""
    ctx = MatchContext(columns, nrows)
    out: List[dict] = []
    start = 0
    while start < nrows:
        ctx.bindings = []
        matched_end = None
        for end in _match_term(pattern, start, ctx, defines, None):
            if end > start:  # ignore empty matches
                matched_end = end
                break
        if matched_end is None:
            start += 1
            continue
        ctx.match_number += 1
        if all_rows:
            match_rows = [r for r, _ in ctx.bindings]
            for r in match_rows:
                row = {"__row__": r}
                # RUNNING semantics: navigation sees the mapping up to r
                full = list(ctx.bindings)
                ctx.bindings = [b for b in full if b[0] <= r]
                for name, expr in measures:
                    row[name] = ctx.eval(expr, r)
                ctx.bindings = full
                out.append(row)
        else:
            last_row = ctx.bindings[-1][0] if ctx.bindings else start
            row = {}
            for name, expr in measures:
                row[name] = ctx.eval(expr, last_row)
            out.append(row)
        if after_match == "to_next_row":
            start = start + 1
        else:
            start = max(matched_end, start + 1)
    return out
