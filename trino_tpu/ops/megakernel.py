"""Fused scan->filter->aggregate megakernels.

The hot TPC-H aggregation fragments (Q6: filter + global sums; Q1:
filter + low-cardinality grouped multi-aggregate) normally lower to a
chain of XLA ops that each re-read the scan columns from HBM: the
filter mask, one select+sum per aggregate plane, one count per
aggregate.  This module collapses the whole Filter*/Project*/Aggregate
chain over a TableScan into ONE grid-free pallas kernel
(ops/pallas_kernels.fused_agg_sums) that streams every referenced scan
column through VMEM exactly once and accumulates every (term, group)
partial in registers.

The fusion is only attempted when it is PROVEN exact at plan time:

  - every referenced scan column has connector statistics with
    null_fraction == 0 and a known [min, max] range (interval
    arithmetic then bounds every intermediate of the compiled
    expressions);
  - all in-kernel arithmetic stays in int32 (the recorded Mosaic
    constraint: in-kernel int64 conversion recurses), so every
    expression node's proven interval must fit int32;
  - each aggregate input decomposes into int32-safe TERMS whose
    per-chunk partial sums cannot wrap: raw values bounded by
    TERM_MAX, 16-bit planes of values bounded by int32, and for one
    level of oversized products a 16-bit limb split of the long factor
    against a short (<= 15-bit) factor -- the exact decomposition the
    flight-recorder bench rounds validated for Q1's extendedprice *
    (1 - discount) * (1 + tax);
  - the whole-table int64 sum of each input is bounded below 2^62
    (stats row count x value bound), so cross-chunk int64
    accumulation and the plane/limb recombination shifts are exact.

Anything unproven raises Reject and the executor silently falls back
to the unfused path -- fusion is an optimization, never a semantics
change.  Group keys ride the same mixed-radix dense group-id scheme as
ops/aggregation.direct_group_ids (dictionary/boolean domains, capacity
<= pallas_kernels.MAX_GROUPS) computed INSIDE the kernel, and the
accumulator layout emitted here is byte-identical to
ops/aggregation.accumulate's narrow fast path, so agg_ops.finalize and
the PARTIAL/FINAL exchange contract are reused unchanged.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..expr import ir
from ..plan import nodes as P
from . import aggregation as agg_ops
from . import pallas_kernels as pk
from . import wide_decimal as wd

I32_MAX = 2 ** 31 - 1
# one [CHUNK_ROWS, 128] column of raw values this small sums in int32
# without wrapping (CHUNK_ROWS * TERM_MAX < 2^31)
TERM_MAX = I32_MAX // pk.CHUNK_ROWS
# whole-table int64 sum headroom: rows * bound must stay below this
SUM_GATE = 2 ** 62
# short factor cap for the limb split: 0xFFFF * LIMB_B_MAX < 2^31
LIMB_B_MAX = 32767

FUSABLE_KINDS = ("sum", "avg", "count", "count_star")

_CMP = {
    "=": lambda a, b: a == b,
    "<>": lambda a, b: a != b,
    "!=": lambda a, b: a != b,
    "is_distinct": lambda a, b: a != b,  # exact: inputs proven null-free
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


class Reject(Exception):
    """Fusion not applicable; the message lands in kernel_profile."""


def _scale(t) -> int:
    return int(t.scale) if getattr(t, "is_decimal", False) else 0


_INT_KINDS = ("bigint", "integer", "smallint", "tinyint", "date",
              "time", "timestamp")


def _int_kind(t) -> bool:
    return bool(getattr(t, "is_decimal", False)) or t.name in _INT_KINDS


@dataclasses.dataclass
class _CV:
    """A compiled kernel value: ``fn(tiles) -> int32 array`` plus the
    interval [lo, hi] and decimal scale proven at plan time."""

    fn: Callable
    lo: int
    hi: int
    scale: int


def _check32(lo: int, hi: int, what: str) -> None:
    if lo < -I32_MAX or hi > I32_MAX:
        raise Reject(f"{what} interval [{lo}, {hi}] exceeds int32")


class _Compiler:
    """Restricted Expr -> in-kernel int32 compiler with interval
    arithmetic.  ``env`` maps scan symbols to their stats-proven
    bounds; every column touched is recorded in ``used`` so the runner
    uploads exactly the referenced tiles."""

    def __init__(self, env: Dict[str, dict]):
        self.env = env
        self.used: List[str] = []

    # -- columns -------------------------------------------------------
    def _info(self, name: str) -> dict:
        info = self.env.get(name)
        if info is None:
            raise Reject(f"column {name} lacks null-free bounded stats")
        return info

    def col(self, name: str) -> _CV:
        info = self._info(name)
        if info.get("dict"):
            raise Reject(f"dictionary column {name} in value position")
        if name not in self.used:
            self.used.append(name)
        return _CV(lambda t, nm=name: t[nm],
                   info["lo"], info["hi"], info["scale"])

    # -- values --------------------------------------------------------
    def value(self, e: ir.Expr) -> _CV:
        if isinstance(e, ir.ColumnRef):
            if e.type.name == "boolean":
                raise Reject("boolean column in value position")
            return self.col(e.name)
        if isinstance(e, ir.Constant):
            if e.value is None:
                raise Reject("NULL constant")
            v = int(e.value)
            _check32(v, v, "constant")
            return _CV(lambda t, c=v: c, v, v, _scale(e.type))
        if isinstance(e, ir.Cast):
            if not (_int_kind(e.type) and _int_kind(e.term.type)):
                raise Reject(f"cast to {e.type.name}")
            return self._rescaled(self.value(e.term), _scale(e.type))
        if isinstance(e, ir.Call):
            return self._call(e)
        raise Reject(f"unfusable value node {type(e).__name__}")

    def _rescaled(self, cv: _CV, scale: int) -> _CV:
        k = scale - cv.scale
        if k < 0:
            raise Reject("rescale down (rounding) in kernel")
        if k == 0:
            return dataclasses.replace(cv, scale=scale)
        m = 10 ** k
        lo, hi = cv.lo * m, cv.hi * m
        _check32(lo, hi, "rescale")
        return _CV(lambda t, f=cv.fn, m=m: f(t) * m, lo, hi, scale)

    def _call(self, e: ir.Call) -> _CV:
        s = _scale(e.type)
        if e.name in ("add", "subtract"):
            l = self._rescaled(self.value(e.args[0]), s)
            r = self._rescaled(self.value(e.args[1]), s)
            if e.name == "add":
                lo, hi = l.lo + r.lo, l.hi + r.hi
                fn = lambda t, f=l.fn, g=r.fn: f(t) + g(t)  # noqa: E731
            else:
                lo, hi = l.lo - r.hi, l.hi - r.lo
                fn = lambda t, f=l.fn, g=r.fn: f(t) - g(t)  # noqa: E731
            _check32(lo, hi, e.name)
            return _CV(fn, lo, hi, s)
        if e.name == "negate":
            v = self.value(e.args[0])
            v = self._rescaled(v, s)
            return _CV(lambda t, f=v.fn: -f(t), -v.hi, -v.lo, s)
        if e.name == "multiply":
            l = self.value(e.args[0])
            r = self.value(e.args[1])
            corners = [l.lo * r.lo, l.lo * r.hi, l.hi * r.lo, l.hi * r.hi]
            lo, hi = min(corners), max(corners)
            _check32(lo, hi, "product")
            prod = _CV(
                lambda t, f=l.fn, g=r.fn: f(t) * g(t),
                lo, hi, l.scale + r.scale,
            )
            return self._rescaled(prod, s)
        raise Reject(f"unfusable call {e.name}")

    # -- predicates ----------------------------------------------------
    def pred(self, e: ir.Expr):
        if isinstance(e, ir.Logical):
            fns = [self.pred(t) for t in e.terms]
            if e.op == "and":
                return lambda t, fs=fns: _fold(fs, t, True)
            if e.op == "or":
                return lambda t, fs=fns: _fold(fs, t, False)
            raise Reject(f"logical op {e.op}")
        if isinstance(e, ir.Not):
            f = self.pred(e.term)
            return lambda t, f=f: jnp.logical_not(f(t))
        if isinstance(e, ir.Comparison):
            return self._cmp(e.op, e.left, e.right)
        if isinstance(e, ir.Between):
            lo = self._cmp("<=", e.low, e.value)
            hi = self._cmp("<=", e.value, e.high)
            if e.negate:
                return lambda t, a=lo, b=hi: jnp.logical_not(a(t) & b(t))
            return lambda t, a=lo, b=hi: a(t) & b(t)
        if isinstance(e, ir.In):
            if not all(isinstance(i, ir.Constant) for i in e.items):
                raise Reject("IN over non-constant items")
            eqs = [self._cmp("=", e.value, i) for i in e.items]
            if e.negate:
                return lambda t, fs=eqs: jnp.logical_not(_fold(fs, t, False))
            return lambda t, fs=eqs: _fold(fs, t, False)
        if isinstance(e, ir.Constant) and e.type.name == "boolean":
            if e.value is None:
                raise Reject("NULL boolean constant")
            return lambda t, c=bool(e.value): c
        if isinstance(e, ir.ColumnRef) and e.type.name == "boolean":
            info = self._info(e.name)
            if not info.get("bool"):
                raise Reject("boolean column lacks stats")
            if e.name not in self.used:
                self.used.append(e.name)
            return lambda t, nm=e.name: t[nm] != 0
        raise Reject(f"unfusable predicate node {type(e).__name__}")

    def _cmp(self, op: str, left: ir.Expr, right: ir.Expr):
        cmp = _CMP.get(op)
        if cmp is None:
            raise Reject(f"comparison op {op}")
        l = self.value(left)
        r = self.value(right)
        m = max(l.scale, r.scale)
        l = self._rescaled(l, m)
        r = self._rescaled(r, m)
        return lambda t, f=l.fn, g=r.fn, c=cmp: c(f(t), g(t))

    # -- aggregate-input term decomposition ----------------------------
    def decompose(self, e: ir.Expr) -> Tuple[List[Tuple[Callable, int]], int]:
        """Split one aggregate input into int32-safe (fn, shift) terms
        whose shifted per-group sums recombine to the exact value sum.
        Returns (terms, value upper bound)."""
        try:
            cv = self.value(e)
        except Reject:
            cv = None
        terms: List[Tuple[Callable, int]] = []
        if cv is not None:
            if cv.lo < 0:
                raise Reject("negative aggregate input")
            _planes(cv.fn, cv.hi, 0, terms)
            return terms, cv.hi
        # one oversized level allowed: a product whose long factor fits
        # int32 and whose short factor fits 15 bits -- split the long
        # factor into 16-bit limbs, multiply each by the short factor
        if not (isinstance(e, ir.Call) and e.name == "multiply"
                and len(e.args) == 2):
            raise Reject("aggregate input exceeds int32 and is no product")
        a = self.value(e.args[0])
        b = self.value(e.args[1])
        if a.hi < b.hi:
            a, b = b, a
        k = _scale(e.type) - (a.scale + b.scale)
        if k < 0:
            raise Reject("oversized product rescales down")
        b = self._rescaled(b, b.scale + k)  # fold 10^k into short factor
        if a.lo < 0 or b.lo < 0:
            raise Reject("negative factor in oversized product")
        if b.hi > LIMB_B_MAX:
            raise Reject("no short factor for limb split")
        hi_lo = 0xFFFF * b.hi
        hi_hi = (a.hi >> 16) * b.hi
        _check32(0, max(hi_lo, hi_hi), "limb product")
        p_lo = lambda t, f=a.fn, g=b.fn: (f(t) & 0xFFFF) * g(t)  # noqa: E731
        p_hi = lambda t, f=a.fn, g=b.fn: (f(t) >> 16) * g(t)  # noqa: E731
        _planes(p_lo, hi_lo, 0, terms)
        _planes(p_hi, hi_hi, 16, terms)
        return terms, a.hi * b.hi


def _planes(fn: Callable, hi: int, shift: int, out: list) -> None:
    """Append fn as one raw term, or as two 16-bit planes when one
    chunk-column of raw values could wrap int32."""
    if hi <= TERM_MAX:
        out.append((fn, shift))
        return
    out.append(((lambda t, f=fn: f(t) & 0xFFFF), shift))
    out.append(((lambda t, f=fn: f(t) >> 16), shift + 16))


def _fold(fns, tiles, conj: bool):
    acc = None
    for f in fns:
        v = f(tiles)
        if acc is None:
            acc = v
        else:
            acc = (acc & v) if conj else (acc | v)
    return acc


def _conjuncts(e: ir.Expr) -> List[ir.Expr]:
    if isinstance(e, ir.Logical) and e.op == "and":
        out: List[ir.Expr] = []
        for t in e.terms:
            out.extend(_conjuncts(t))
        return out
    return [e]


# ----------------------------------------------------------------------
# matcher


def _match(ctx, node: P.Aggregate):
    if node.step not in ("single", "partial"):
        raise Reject(f"step {node.step}")
    if not node.aggs:
        raise Reject("no aggregates")
    for a in node.aggs:
        if a.distinct:
            raise Reject("DISTINCT aggregate")
        if a.kind not in FUSABLE_KINDS:
            raise Reject(f"aggregate kind {a.kind}")
    if getattr(ctx.lowering, "force_wide_mul", False):
        raise Reject("wide-multiply retry rung")
    chain: List[P.PlanNode] = []
    cur = node.source
    while isinstance(cur, (P.Project, P.Filter)):
        chain.append(cur)
        cur = cur.source
    if not isinstance(cur, P.TableScan):
        raise Reject("source is not a Filter/Project chain over a scan")
    scan = cur
    # compose the chain bottom-up into expressions over scan symbols
    mapping: Dict[str, ir.Expr] = {
        s: ir.ColumnRef(t, s) for s, t in scan.types
    }
    preds: List[ir.Expr] = []
    for nd in reversed(chain):
        if isinstance(nd, P.Filter):
            preds.extend(_conjuncts(ir.replace_refs(nd.predicate, mapping)))
        else:
            mapping = {
                s: ir.replace_refs(e, mapping) for s, e in nd.assignments
            }
    return scan, mapping, preds


def _column_env(ex, scan: P.TableScan, types) -> Tuple[Dict[str, dict], object]:
    try:
        stats = ex.metadata.table_statistics(scan.catalog, scan.table)
    except Exception:
        raise Reject("no table statistics")
    env: Dict[str, dict] = {}
    for sym, col in scan.assignments:
        t = types[sym]
        cs = stats.columns.get(col)
        if cs is None or cs.null_fraction:
            continue  # unusable: any reference rejects fusion
        if t.is_dictionary:
            env[sym] = {"dict": True}
            continue
        if t.name == "boolean":
            env[sym] = {"lo": 0, "hi": 1, "scale": 0, "bool": True}
            continue
        if cs.min_value is None or cs.max_value is None:
            continue
        lo = int(math.floor(cs.min_value))
        hi = int(math.ceil(cs.max_value))
        if lo < -I32_MAX or hi > I32_MAX:
            continue
        env[sym] = {"lo": lo, "hi": hi, "scale": _scale(t)}
    return env, stats


def _key_domains(ex, node: P.Aggregate, mapping, types, env):
    """Mixed-radix dense grouping over dictionary/boolean scan columns
    -- the in-kernel mirror of ops/aggregation.direct_group_ids (radix
    dom+1 per key keeps the unfused NULL slot layout, so capacities and
    group ids agree exactly with the fallback path)."""
    doms: List[Tuple[str, str, int]] = []
    cap = 1
    for k in node.keys:
        e = mapping.get(k)
        if not isinstance(e, ir.ColumnRef):
            raise Reject(f"group key {k} is not a scan column")
        sk = e.name
        info = env.get(sk)
        if info is None:
            raise Reject(f"group key {sk} lacks null-free stats")
        if info.get("dict"):
            d = ex.dicts.get(sk)
            if d is None or len(d) == 0:
                raise Reject(f"no dictionary for key {sk}")
            dom = len(d)
        elif info.get("bool"):
            dom = 2
        else:
            raise Reject(f"group key {sk} is not low-cardinality")
        doms.append((k, sk, dom))
        cap *= dom + 1
    if node.keys and cap > pk.MAX_GROUPS:
        raise Reject(f"group capacity {cap} > {pk.MAX_GROUPS}")
    return doms, (cap if node.keys else 1)


# ----------------------------------------------------------------------
# entry point


def try_fused(ctx, node: P.Aggregate):
    """Attempt the fused megakernel for this Aggregate; returns the
    finished Batch or None (caller runs the unfused path)."""
    ex = ctx.ex
    if ex._megakernel_mode() != "on":
        return None
    if not pk.HAVE_PALLAS:
        return None
    try:
        return _run(ctx, node)
    except Reject as r:
        prof = ex.kernel_profile
        prof["fusionRejects"] = prof.get("fusionRejects", 0) + 1
        prof["lastFusionReject"] = str(r)
        from ..obs import journal

        journal.emit(
            journal.FUSION_REJECT,
            query_id=getattr(ex, "query_id", "") or "",
            reason=str(r)[:200],
        )
        return None


def _run(ctx, node: P.Aggregate):
    ex = ctx.ex
    scan, mapping, preds = _match(ctx, node)
    types = dict(scan.types)
    env, stats = _column_env(ex, scan, types)
    doms, cap = _key_domains(ex, node, mapping, types, env)

    comp = _Compiler(env)
    pred_fns = [comp.pred(p) for p in preds]

    # term 0 is always the live-row count (the $valid/$count lane every
    # fused kind shares); value terms append after it, deduplicated by
    # structural expression equality (sum+avg over one column share)
    terms: List[Tuple[Callable, int]] = [((lambda t: 1), 0)]
    rows_bound = max(int(stats.row_count), 1) + 256  # pad-capacity slack
    input_terms: Dict[ir.Expr, List[Tuple[int, int]]] = {}
    plans: List[Optional[List[Tuple[int, int]]]] = []
    for a in node.aggs:
        if a.kind == "count_star":
            plans.append(None)
            continue
        e = mapping.get(a.arg)
        if e is None:
            raise Reject(f"aggregate arg {a.arg} escapes the fused chain")
        if a.kind == "count":
            # null-free inputs make count(x) == count(live rows); only
            # prove the references are null-free, no value needed
            for c in ir.referenced_columns(e):
                if env.get(c) is None:
                    raise Reject(f"count over unproven column {c}")
            plans.append(None)
            continue
        slots = input_terms.get(e)
        if slots is None:
            tlist, hi = comp.decompose(e)
            if rows_bound * hi >= SUM_GATE:
                raise Reject("table-wide sum could exceed int64")
            slots = []
            for fn, sh in tlist:
                slots.append((len(terms), sh))
                terms.append((fn, sh))
            input_terms[e] = slots
        plans.append(slots)

    # the kernel reads each referenced column plus the key columns once
    names = list(comp.used)
    for _k, sk, _dom in doms:
        if sk not in names:
            names.append(sk)

    def emit(tiles):
        p = _fold(pred_fns, tiles, True) if pred_fns else None
        gid = None
        for _k, sk, dom in doms:
            code = jnp.clip(tiles[sk], 0, dom - 1)
            gid = code if gid is None else gid * (dom + 1) + code
        return p, gid, [fn(tiles) for fn, _sh in terms]

    # -- runner (still inside the fragment trace) ----------------------
    b = ctx.visit(scan)
    live = b.sel
    cols32 = {}
    for nm in names:
        v, ok = b.lanes[nm]
        if v.ndim != 1 or v.dtype.kind not in ("i", "u"):
            raise Reject(f"column {nm} lane is not a narrow integer")
        if ok is not None:
            live = live & ok
        cols32[nm] = v.astype(jnp.int32)

    n_terms = len(terms)
    sums = pk.fused_agg_sums(
        cols32, live, emit, n_terms, cap,
        interpret=not pk.enabled(),
    )
    # mesh shard bodies: each device fused ITS split shard; the trace
    # context merges the int64 (term, group) partials across the mesh
    # before the shared finalize tail (identity on a single device).
    # The SUM_GATE proof above bounds the TABLE-wide total, so the
    # cross-shard sum of per-shard partials cannot wrap int64.
    sums = ctx._merge_fused_sums(sums)
    cnt = sums[0]

    specs = [a.to_spec() for a in node.aggs]
    accs: Dict[str, jnp.ndarray] = {}
    for s, slots in zip(specs, plans):
        o = s.output
        if slots is None:  # count / count_star
            accs[f"{o}$count"] = cnt
            continue
        val = jnp.zeros_like(cnt)
        for i, sh in slots:
            val = val + (sums[i] << jnp.int64(sh))
        if s._wide_sum:
            # narrow fast path of the wide accumulator schema: the sum
            # is proven to fit int64, shipped as 32-bit chunk lanes
            cs = wd.normalize_chunks([
                val & 0xFFFFFFFF, val >> jnp.int64(32),
                jnp.zeros_like(val), jnp.zeros_like(val),
            ])
            for i, c in enumerate(cs):
                accs[f"{o}$c{i}"] = c
            accs[f"{o}$valid" if s.kind == "sum" else f"{o}$count"] = cnt
        elif s.kind == "sum":
            accs[f"{o}$val"] = val
            accs[f"{o}$valid"] = cnt
        else:  # narrow avg
            accs[f"{o}$sum"] = val
            accs[f"{o}$count"] = cnt

    if node.step == "partial":
        out = {
            nm: (v, jnp.ones(v.shape, bool)) for nm, v in accs.items()
        }
    else:
        out = agg_ops.finalize(specs, accs)

    keys_out = []
    if node.keys:
        # arithmetic key decode: slot -> per-key dictionary codes (the
        # mixed-radix inverse of the in-kernel gid); code == dom is the
        # never-hit NULL slot, masked by present anyway
        rem = jnp.arange(cap, dtype=jnp.int64)
        codes: List[jnp.ndarray] = [None] * len(doms)  # type: ignore
        for i in range(len(doms) - 1, -1, -1):
            radix = doms[i][2] + 1
            codes[i] = rem % radix
            rem = rem // radix
        for (k, sk, dom), code in zip(doms, codes):
            kv, _kok = b.lanes[sk]
            keys_out.append((code.astype(kv.dtype), code < dom))
            if k != sk and sk in ex.dicts:
                ex.dicts.setdefault(k, ex.dicts[sk])
        present = cnt > 0
    else:
        present = jnp.ones(1, bool)

    prof = ex.kernel_profile
    prof["fusedAggregates"] = prof.get("fusedAggregates", 0) + 1
    prof["fusedTerms"] = prof.get("fusedTerms", 0) + n_terms
    ex._record_kernel(
        "megakernel:%s/t%d/g%d" % (scan.table, n_terms, cap),
        0.0, True, mode="megakernel",
    )
    return ctx._finish_aggregate(node, keys_out, out, present, cap)
