"""Wide (two-limb) decimal storage and aggregation: decimal(19..38).

Reference parity: spi/type/Int128.java, Int128Math.java and
block/Int128ArrayBlock.java:28 — the reference stores long decimals as
two-limb 128-bit values and aggregates them with Int128Math add/divide.

TPU-first redesign:
  - A wide decimal *lane* is one int64 array of shape (n, 2):
    [:, 0] the low limb (bit pattern, unsigned semantics) and [:, 1] the
    high limb (signed).  A single array (not a companion symbol) rides
    through every generic gather/permute untouched, keeps plan symbol
    lists one-to-one with lanes, and stays a legal single jax value in
    jitted fragment signatures.
  - SUM accumulator state is four *32-bit chunk sums* stored in int64
    lanes (`$c0..$c3`, little-endian chunks, top chunk signed).  A
    segment-sum of 32-bit chunks cannot overflow int64 below 2^31 rows,
    so accumulation is two (narrow input) or four (wide input) ordinary
    segment_sums — no carry logic inside the hot loop.  Carries are
    propagated once per *capacity* (`normalize_chunks`), and chunk sums
    are mergeable by plain addition, which makes the cross-device merge
    a psum per chunk lane (ICI-friendly) instead of a custom collective.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import int128

# python ints, NOT jnp scalars: module-level jnp constants become hidden
# const ARGUMENTS of every jitted program that touches them (visible as
# %arg0 tensor<i64> in the lowered HLO); plain ints fold into literals
_M32 = 0xFFFFFFFF
_SIGN64 = -0x8000000000000000  # 1 << 63 as the int64 bit pattern

WIDE_DIGITS = 18  # precision above this needs two limbs


def is_wide_type(t) -> bool:
    return (
        t is not None
        and getattr(t, "is_decimal", False)
        and t.precision > WIDE_DIGITS
    )


def is_wide(v: jnp.ndarray) -> bool:
    """Is this lane value array a wide (two-limb) decimal?"""
    return v.ndim == 2


def widen(v: jnp.ndarray) -> jnp.ndarray:
    """Promote a narrow int64 lane to wide: hi = sign extension."""
    v = v.astype(jnp.int64)
    return jnp.stack([v, v >> jnp.int64(63)], axis=-1)


def make_wide(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([lo.astype(jnp.int64), hi.astype(jnp.int64)], axis=-1)


def limbs(w: jnp.ndarray):
    """(lo, hi) int64 views of a wide lane."""
    return w[..., 0], w[..., 1]


def narrow(w: jnp.ndarray) -> jnp.ndarray:
    """Low limb (callers must know the value fits 64 bits)."""
    return w[..., 0]


def fits_narrow(w: jnp.ndarray) -> jnp.ndarray:
    """Per-row: does the 128-bit value fit a signed int64?"""
    lo, hi = limbs(w)
    return hi == (lo >> jnp.int64(63))


# -- ordering ----------------------------------------------------------
def order_operands(w: jnp.ndarray, descending: bool = False):
    """Two int64 sort operands (major, minor) whose joint lexicographic
    order equals signed 128-bit order.  The low limb is unsigned, so its
    sign bit is flipped into signed order; DESC complements both."""
    lo, hi = limbs(w)
    lo_s = lo ^ _SIGN64
    if descending:
        return ~hi, ~lo_s
    return hi, lo_s


def order_approx64(w: jnp.ndarray) -> jnp.ndarray:
    """Monotone int64 approximation of 128-bit order: EXACT (= the low
    limb) for values that fit int64, sign-saturated for genuinely wide
    values.  Distinct wide values may collapse to the saturation ties,
    never reorder; TopN phase 1 counts encoded ties, so collapses are
    exactness-safe.  (The previous floor(v/2^32) form collapsed every
    ordinary-magnitude decimal sum — e.g. all of TPC-H Q3's revenues —
    into one tie, forcing the TopN ladder through 3 recompiles into a
    full sort.)"""
    lo, hi = limbs(w)
    sat = jnp.where(
        hi < 0, jnp.int64(-(2**63)), jnp.int64(2**63 - 1)
    )
    return jnp.where(fits_narrow(w), lo, sat)


def compare(a: jnp.ndarray, b: jnp.ndarray, op: str) -> jnp.ndarray:
    """Elementwise signed 128-bit comparison of two wide lanes."""
    alo, ahi = limbs(a)
    blo, bhi = limbs(b)
    alo_u = alo ^ _SIGN64  # unsigned order in the signed domain
    blo_u = blo ^ _SIGN64
    lt = (ahi < bhi) | ((ahi == bhi) & (alo_u < blo_u))
    eq = (ahi == bhi) & (alo == blo)
    if op == "<":
        return lt
    if op == "<=":
        return lt | eq
    if op == ">":
        return ~(lt | eq)
    if op == ">=":
        return ~lt
    if op == "==":
        return eq
    if op == "!=":
        return ~eq
    raise ValueError(op)


# -- arithmetic --------------------------------------------------------
def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """128-bit wraparound addition of two wide lanes."""
    alo, ahi = limbs(a)
    blo, bhi = limbs(b)
    lo = (alo.astype(jnp.uint64) + blo.astype(jnp.uint64))
    carry = (lo < alo.astype(jnp.uint64)).astype(jnp.int64)
    return make_wide(lo.astype(jnp.int64), ahi + bhi + carry)


def negate(a: jnp.ndarray) -> jnp.ndarray:
    lo, hi = limbs(a)
    nlo = (~lo).astype(jnp.uint64) + jnp.uint64(1)
    carry = (nlo == 0).astype(jnp.int64)
    return make_wide(nlo.astype(jnp.int64), ~hi + carry)


def subtract(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return add(a, negate(b))


def abs128(a: jnp.ndarray):
    """(|a| as wide, sign) — sign is -1/+1 int64."""
    lo, hi = limbs(a)
    neg = hi < 0
    mag = jnp.where(neg[..., None], negate(a), a)
    return mag, jnp.where(neg, jnp.int64(-1), jnp.int64(1))


def rescale(w: jnp.ndarray, up: int) -> jnp.ndarray:
    """w * 10^up (up >= 0) in 128-bit wraparound arithmetic; callers
    bound the result to < 2^127 via precision rules."""
    if up == 0:
        return w
    mag, sign = abs128(w)
    lo, hi = limbs(mag)
    c = 10**up
    if c >= 1 << 63:
        raise NotImplementedError("rescale beyond 10^18 in one step")
    hi_p, lo_p = int128.umul128(lo.astype(jnp.uint64), jnp.uint64(c))
    hi_p = hi_p + hi.astype(jnp.uint64) * jnp.uint64(c)
    out = make_wide(lo_p.astype(jnp.int64), hi_p.astype(jnp.int64))
    return jnp.where((sign < 0)[..., None], negate(out), out)


def div_round(w: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """round_half_away(w / d) for a wide lane over positive int64
    divisors d (per-element); returns a wide lane with a FULL 128-bit
    quotient (restoring division, 128 fixed iterations)."""
    mag, sign = abs128(w)
    lo64, hi64 = limbs(mag)
    hi = hi64.astype(jnp.uint64)
    lo = lo64.astype(jnp.uint64)
    dd = jnp.maximum(d, 1).astype(jnp.uint64)
    one = jnp.uint64(1)

    def body(i, st):
        rem, qhi, qlo = st
        bit_index = jnp.uint64(127) - jnp.uint64(i)
        word = jnp.where(bit_index >= jnp.uint64(64), hi, lo)
        sh = jnp.where(
            bit_index >= jnp.uint64(64),
            bit_index - jnp.uint64(64),
            bit_index,
        )
        bit = (word >> sh) & one
        rem = (rem << one) | bit
        ge = rem >= dd
        rem = jnp.where(ge, rem - dd, rem)
        qhi = (qhi << one) | (qlo >> jnp.uint64(63))
        qlo = (qlo << one) | ge.astype(jnp.uint64)
        return rem, qhi, qlo

    z = jnp.zeros_like(dd)
    rem, qhi, qlo = jax.lax.fori_loop(0, 128, body, (z, z, z))
    up = (jnp.uint64(2) * rem >= dd).astype(jnp.uint64)
    qlo2 = qlo + up
    qhi = qhi + (qlo2 < qlo).astype(jnp.uint64)
    out = make_wide(qlo2.astype(jnp.int64), qhi.astype(jnp.int64))
    return jnp.where((sign < 0)[..., None], negate(out), out)


def _udiv128_const_wide(hi: jnp.ndarray, lo: jnp.ndarray, const: int):
    """Unsigned (hi:lo) / trace-time const -> 128-bit quotient (qhi, qlo)
    + 64-bit remainder-ish (rem fits one limb for const < 2^63).
    Restoring division, 128 fixed iterations (Int128Math.divide role)."""
    dhi = jnp.uint64(const >> 64)
    dlo = jnp.uint64(const & ((1 << 64) - 1))
    one = jnp.uint64(1)

    def body(i, st):
        rhi, rlo, qhi, qlo = st
        bit_index = jnp.uint64(127) - jnp.uint64(i)
        word = jnp.where(bit_index >= jnp.uint64(64), hi, lo)
        sh = jnp.where(
            bit_index >= jnp.uint64(64),
            bit_index - jnp.uint64(64),
            bit_index,
        )
        bit = (word >> sh) & one
        rhi = (rhi << one) | (rlo >> jnp.uint64(63))
        rlo = (rlo << one) | bit
        ge = (rhi > dhi) | ((rhi == dhi) & (rlo >= dlo))
        borrow = (rlo < dlo).astype(jnp.uint64)
        rhi = jnp.where(ge, rhi - dhi - borrow, rhi)
        rlo = jnp.where(ge, rlo - dlo, rlo)
        qhi = (qhi << one) | (qlo >> jnp.uint64(63))
        qlo = (qlo << one) | ge.astype(jnp.uint64)
        return rhi, rlo, qhi, qlo

    z = jnp.zeros_like(lo)
    rhi, rlo, qhi, qlo = jax.lax.fori_loop(0, 128, body, (z, z, z, z))
    return qhi, qlo, rhi, rlo


def mul_wide(l: jnp.ndarray, r: jnp.ndarray, down: int) -> jnp.ndarray:
    """Exact signed product of two lanes (narrow or wide) rescaled down
    by 10^down with round-half-away, as a wide lane.  Exact while the
    unscaled |product| < 2^127 (guaranteed when operand precisions sum
    to <= 38, the DecimalType cap)."""
    lm, ls = abs128(promote(l))
    rm, rs = abs128(promote(r))
    llo, lhi = limbs(lm)
    rlo, rhi = limbs(rm)
    llo_u = llo.astype(jnp.uint64)
    rlo_u = rlo.astype(jnp.uint64)
    hi, lo = int128.umul128(llo_u, rlo_u)
    # cross terms wrap into the high limb (product bounded < 2^127)
    hi = (
        hi
        + llo_u * rhi.astype(jnp.uint64)
        + lhi.astype(jnp.uint64) * rlo_u
    )
    if down > 0:
        const = 10**down
        qhi, qlo, rhi_r, rlo_r = _udiv128_const_wide(hi, lo, const)
        # round half away: 2*rem >= const (rem < const <= 10^38 < 2^127)
        r2hi = (rhi_r << jnp.uint64(1)) | (rlo_r >> jnp.uint64(63))
        r2lo = rlo_r << jnp.uint64(1)
        chi = jnp.uint64(const >> 64)
        clo = jnp.uint64(const & ((1 << 64) - 1))
        up = ((r2hi > chi) | ((r2hi == chi) & (r2lo >= clo))).astype(
            jnp.uint64
        )
        qlo2 = qlo + up
        qhi = qhi + (qlo2 < qlo).astype(jnp.uint64)
        hi, lo = qhi, qlo2
    mag = make_wide(lo.astype(jnp.int64), hi.astype(jnp.int64))
    neg = (ls * rs) < 0
    return jnp.where(neg[..., None], negate(mag), mag)


# -- chunked accumulator form ------------------------------------------
def narrow_row_chunks(v: jnp.ndarray, live: jnp.ndarray):
    """Per-row 32-bit chunks of a narrow int64 lane: [c0 (unsigned),
    c1 (signed high)] — v == c1*2^32 + c0 exactly."""
    vv = jnp.where(live, v.astype(jnp.int64), 0)
    return [vv & _M32, vv >> jnp.int64(32)]


def wide_row_chunks(w: jnp.ndarray, live: jnp.ndarray):
    """Per-row 32-bit chunks of a wide lane: [c0..c3], c3 signed."""
    lo, hi = limbs(w)
    lo = jnp.where(live, lo, 0)
    hi = jnp.where(live, hi, 0)
    return [
        lo & _M32,
        (lo >> jnp.int64(32)) & _M32,  # logical: lo is a bit pattern
        hi & _M32,
        hi >> jnp.int64(32),
    ]


def normalize_chunks(chunks):
    """Propagate carries so every chunk is back in 32-bit range (top
    chunk keeps the sign).  Exact while chunk magnitudes stay < 2^63,
    i.e. < 2^31 accumulated rows — far beyond one device's tile."""
    out = []
    carry = jnp.zeros_like(chunks[0])
    for i, c in enumerate(chunks):
        c = c + carry
        if i == len(chunks) - 1:
            out.append(c)  # top chunk: signed, absorbs remaining carry
        else:
            out.append(c & _M32)
            carry = c >> jnp.int64(32)  # arithmetic: signed carries work
    return out


def chunks_to_wide(chunks) -> jnp.ndarray:
    """Canonical (normalized) chunks -> wide (…, 2) lane."""
    c0, c1, c2, c3 = chunks
    lo = (c1 << jnp.int64(32)) | c0
    hi = (c3 << jnp.int64(32)) | c2
    return make_wide(lo, hi)


def seg_sum_chunks(row_chunks, gid: jnp.ndarray, cap: int):
    """Segment-sum per-row chunk lanes and normalize: the wide SUM
    kernel.  Two-chunk inputs (narrow rows) pad with zero chunks —
    `normalize_chunks`' arithmetic carries sign-extend negatives
    correctly through the zero chunks.

    Small capacities use the masked-matrix reduction per chunk lane
    (XLA:TPU scatter measured ~16M updates/s vs ~100x that for the
    masked form at cap<=32 — MICRO_group.json); large capacities fall
    back to one stacked (n, k) scatter."""
    from .aggregation import _use_masked

    if _use_masked(cap):
        from .aggregation import _seg_sum

        sums = [_seg_sum(c, gid, cap) for c in row_chunks]
    else:
        mat = jnp.stack(row_chunks, axis=1)  # (n, k)
        sums2 = jax.ops.segment_sum(mat, gid, num_segments=cap)
        sums = [sums2[:, i] for i in range(len(row_chunks))]
    while len(sums) < 4:
        sums.append(jnp.zeros_like(sums[0]))
    return normalize_chunks(sums)


def merge_chunk_lanes(chunk_lanes, w, gid, cap):
    """FINAL-step merge of shipped (canonical) chunk columns: plain
    segment sums + one carry pass.  Exact while the merged partial
    count stays < 2^31 (chunks < 2^32 each)."""
    sums = [
        jax.ops.segment_sum(jnp.where(w, c, 0), gid, num_segments=cap)
        for c in chunk_lanes
    ]
    return normalize_chunks(sums)


def promote(v: jnp.ndarray) -> jnp.ndarray:
    """Lane value -> wide form (no-op if already two-limb)."""
    return v if is_wide(v) else widen(v)


def decimal_rescale_wide(w: jnp.ndarray, fs: int, ts: int) -> jnp.ndarray:
    """Scale change on wide lanes with round-half-away (Int128Math
    rescale analog).  Down-rescales keep a FULL 128-bit quotient, so
    e.g. decimal(38,6) -> decimal(38,2) stays exact."""
    if ts >= fs:
        return rescale(w, ts - fs)
    down = fs - ts
    mag, sign = abs128(w)
    lo, hi = limbs(mag)
    const = 10**down
    qhi, qlo, rhi, rlo = _udiv128_const_wide(
        hi.astype(jnp.uint64), lo.astype(jnp.uint64), const
    )
    # round half away: 2*rem >= const (both < 2^127)
    r2hi = (rhi << jnp.uint64(1)) | (rlo >> jnp.uint64(63))
    r2lo = rlo << jnp.uint64(1)
    chi = jnp.uint64(const >> 64)
    clo = jnp.uint64(const & ((1 << 64) - 1))
    up = ((r2hi > chi) | ((r2hi == chi) & (r2lo >= clo))).astype(jnp.uint64)
    qlo2 = qlo + up
    qhi = qhi + (qlo2 < qlo).astype(jnp.uint64)
    out = make_wide(qlo2.astype(jnp.int64), qhi.astype(jnp.int64))
    return jnp.where((sign < 0)[..., None], negate(out), out)


def to_double(w: jnp.ndarray) -> jnp.ndarray:
    """Wide -> float64 (rounds beyond 2^53 like any int64 cast)."""
    lo, hi = limbs(w)
    lo_f = lo.astype(jnp.float64) + jnp.where(
        lo < 0, jnp.float64(2.0**64), jnp.float64(0.0)
    )
    return hi.astype(jnp.float64) * jnp.float64(2.0**64) + lo_f


def pad_rows(v: jnp.ndarray, extra: int) -> jnp.ndarray:
    """Pad axis 0 by `extra` rows, preserving limb dims (narrow- and
    wide-lane safe replacement for jnp.pad(v, (0, extra)))."""
    return jnp.pad(v, ((0, extra),) + ((0, 0),) * (v.ndim - 1))


# -- device <-> host ----------------------------------------------------
def to_python_ints(lo_arr, hi_arr, valid):
    """Host conversion: limb arrays -> python ints (exact)."""
    import numpy as np

    lo = np.asarray(lo_arr).astype(np.uint64)
    hi = np.asarray(hi_arr).astype(np.int64)
    out = []
    for i in range(lo.shape[0]):
        if valid is not None and not valid[i]:
            out.append(None)
        else:
            out.append((int(hi[i]) << 64) | int(lo[i]))
    return out


def from_python_int(x: int):
    """Python int -> (lo, hi) int64 bit patterns."""
    lo = x & ((1 << 64) - 1)
    hi = (x >> 64) & ((1 << 64) - 1)
    if lo >= 1 << 63:
        lo -= 1 << 64
    if hi >= 1 << 63:
        hi -= 1 << 64
    return lo, hi
