"""Group-by aggregation kernels.

Reference parity: operator/HashAggregationOperator.java:53,
operator/GroupByHash.java:29 (FlatGroupByHash/FlatHash open addressing),
operator/aggregation/ (AccumulatorCompiler bytecode accumulators),
aggregation/builder/InMemoryHashAggregationBuilder.java:50.

TPU-first redesign — hash tables with random scatter are hostile to the MXU/
VPU, so grouping uses two strategies (SURVEY §7 "sort-or-scatter group-by"):

  1. direct: group keys that are dictionary codes / small ints map to a
     dense group id by mixed-radix combination; accumulators are
     jax.ops.segment_sum over a static group capacity.  This is the analog
     of the reference's BigintGroupByHash fast path and covers low-
     cardinality group-bys (TPC-H Q1: 2x2 codes -> 6 ids).

  2. sort-based: rows lexicographically sorted by the full key tuple
     (jax.lax.sort multi-operand, exact — no hash collisions), group
     boundaries by adjacent-difference, group ids by prefix sum, then the
     same segment_sum accumulators.  O(n log n) but fully static-shape.

Group capacity is static per compilation; the kernel returns the true group
count so the host can recompile with a larger capacity when exceeded
(the "recompile-on-bucket-change" idiom replacing FlatHash rehashing).

Aggregation steps mirror AggregationNode.Step (plan/AggregationNode.java:346):
PARTIAL produces accumulator columns keyed by group; FINAL re-groups partial
rows and merges accumulators — the same kernel pair handles both, which is
also the distributed merge path (all-gather partials -> final, SURVEY §2.2).

NULL semantics: a NULL key is its own group (tracked via the validity bit as
an extra radix/sort key); sum/min/max ignore NULL inputs and return NULL for
empty groups; count counts non-NULL only.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..expr.lower import Lane

I64_MAX = jnp.int64(2**62)


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate function instance (AggregatorFactory analog)."""

    kind: str  # sum | count | count_star | min | max | avg
    input: Optional[str]  # input column name (None for count_star)
    output: str
    input_type: Optional[T.Type] = None
    output_type: Optional[T.Type] = None
    distinct: bool = False

    @property
    def accumulator_names(self) -> List[str]:
        if self.kind in ("avg",):
            return [f"{self.output}$sum", f"{self.output}$count"]
        if self.kind in ("sum", "min", "max"):
            return [f"{self.output}$val", f"{self.output}$valid"]
        return [f"{self.output}$count"]


def direct_group_ids(
    key_lanes: Sequence[Lane], domains: Sequence[int]
) -> Tuple[jnp.ndarray, int]:
    """Mixed-radix dense group id from small-domain keys.

    Each key contributes radix (domain+1): slot `domain` encodes NULL.
    Returns (gid array, capacity).
    """
    gid = None
    cap = 1
    for (v, ok), dom in zip(key_lanes, domains):
        radix = dom + 1
        code = jnp.where(ok, jnp.clip(v.astype(jnp.int64), 0, dom - 1), dom)
        gid = code if gid is None else gid * radix + code
        cap *= radix
    return gid, cap


def sort_group_ids(
    key_lanes: Sequence[Lane], sel: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort-based grouping: returns (perm, gid_sorted, ngroups).

    perm reorders rows so equal keys are adjacent (unselected rows last);
    gid_sorted[i] is the group id of sorted row i (unselected rows get
    capacity-1 but are excluded by weight later).
    """
    n = key_lanes[0][0].shape[0]
    operands = [jnp.logical_not(sel)]
    for v, ok in key_lanes:
        operands.append(jnp.logical_not(ok))
        operands.append(v)
    operands.append(jnp.arange(n, dtype=jnp.int64))
    num_keys = len(operands) - 1
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=num_keys)
    perm = sorted_ops[-1]
    sel_sorted = jnp.logical_not(sorted_ops[0])
    # boundary: first selected row of a distinct key tuple
    diff = jnp.zeros(n, dtype=bool).at[0].set(True)
    for k in range(1, num_keys):
        col = sorted_ops[k]
        diff = diff | jnp.concatenate([jnp.ones(1, bool), col[1:] != col[:-1]])
    boundary = diff & sel_sorted
    gid = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    ngroups = boundary.sum()
    gid = jnp.where(sel_sorted, jnp.clip(gid, 0, capacity - 1), capacity - 1)
    return perm, gid, ngroups


def distinct_count(
    gid: jnp.ndarray, lane: Lane, sel: jnp.ndarray, capacity: int
) -> jnp.ndarray:
    """count(DISTINCT x) per group: sort by (gid, x), count first
    occurrences (MarkDistinctOperator + count, in one sort)."""
    v, ok = lane
    live = sel & ok
    n = gid.shape[0]
    vv = v.astype(jnp.int64) if v.dtype.kind in ("i", "u", "b") else v
    dead = jnp.logical_not(live)
    # dead rows sort last; within live rows, equal (gid, value) adjacent
    sorted_ops = jax.lax.sort(
        (dead, gid, vv, jnp.arange(n, dtype=jnp.int64)), num_keys=3
    )
    d2, g2, v2, perm = sorted_ops
    live2 = jnp.logical_not(d2)
    first = jnp.concatenate(
        [jnp.ones(1, bool), (g2[1:] != g2[:-1]) | (v2[1:] != v2[:-1])]
    )
    flags = (first & live2).astype(jnp.int64)
    return jax.ops.segment_sum(flags, jnp.clip(g2, 0, capacity - 1),
                               num_segments=capacity)


def accumulate(
    specs: Sequence[AggSpec],
    lanes: Dict[str, Lane],
    gid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
) -> Dict[str, jnp.ndarray]:
    """Compute accumulator arrays (shape [capacity]) per spec."""
    out: Dict[str, jnp.ndarray] = {}
    for s in specs:
        if getattr(s, "distinct", False):
            if s.kind != "count":
                raise NotImplementedError(f"{s.kind}(DISTINCT) not supported")
            out[f"{s.output}$count"] = distinct_count(
                gid, lanes[s.input], sel, capacity
            )
            continue
        if s.kind == "count_star":
            w = sel.astype(jnp.int64)
            out[f"{s.output}$count"] = jax.ops.segment_sum(
                w, gid, num_segments=capacity
            )
            continue
        v, ok = lanes[s.input]
        live = sel & ok
        if s.kind == "count":
            out[f"{s.output}$count"] = jax.ops.segment_sum(
                live.astype(jnp.int64), gid, num_segments=capacity
            )
        elif s.kind in ("sum", "avg"):
            if v.dtype.kind == "f":
                vv = jnp.where(live, v, 0.0)
            else:
                vv = jnp.where(live, v.astype(jnp.int64), 0)
            ssum = jax.ops.segment_sum(vv, gid, num_segments=capacity)
            cnt = jax.ops.segment_sum(
                live.astype(jnp.int64), gid, num_segments=capacity
            )
            if s.kind == "sum":
                out[f"{s.output}$val"] = ssum
                out[f"{s.output}$valid"] = cnt
            else:
                out[f"{s.output}$sum"] = ssum
                out[f"{s.output}$count"] = cnt
        elif s.kind in ("min", "max"):
            if v.dtype.kind == "f":
                sentinel = jnp.inf if s.kind == "min" else -jnp.inf
                vv = jnp.where(live, v, sentinel)
            else:
                sentinel = I64_MAX if s.kind == "min" else -I64_MAX
                vv = jnp.where(live, v.astype(jnp.int64), sentinel)
            seg = jax.ops.segment_min if s.kind == "min" else jax.ops.segment_max
            out[f"{s.output}$val"] = seg(vv, gid, num_segments=capacity)
            out[f"{s.output}$valid"] = jax.ops.segment_sum(
                live.astype(jnp.int64), gid, num_segments=capacity
            )
        else:
            raise NotImplementedError(s.kind)
    return out


def merge_accumulators(
    specs: Sequence[AggSpec],
    acc_lanes: Dict[str, Lane],
    gid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
) -> Dict[str, jnp.ndarray]:
    """FINAL step: merge partial accumulator rows grouped by gid."""
    out: Dict[str, jnp.ndarray] = {}
    w = sel
    for s in specs:
        if s.kind in ("count", "count_star"):
            v, _ = acc_lanes[f"{s.output}$count"]
            out[f"{s.output}$count"] = jax.ops.segment_sum(
                jnp.where(w, v, 0), gid, num_segments=capacity
            )
        elif s.kind == "avg":
            sv, _ = acc_lanes[f"{s.output}$sum"]
            cv, _ = acc_lanes[f"{s.output}$count"]
            zero = 0.0 if sv.dtype.kind == "f" else 0
            out[f"{s.output}$sum"] = jax.ops.segment_sum(
                jnp.where(w, sv, zero), gid, num_segments=capacity
            )
            out[f"{s.output}$count"] = jax.ops.segment_sum(
                jnp.where(w, cv, 0), gid, num_segments=capacity
            )
        elif s.kind == "sum":
            sv, _ = acc_lanes[f"{s.output}$val"]
            cv, _ = acc_lanes[f"{s.output}$valid"]
            zero = 0.0 if sv.dtype.kind == "f" else 0
            out[f"{s.output}$val"] = jax.ops.segment_sum(
                jnp.where(w, sv, zero), gid, num_segments=capacity
            )
            out[f"{s.output}$valid"] = jax.ops.segment_sum(
                jnp.where(w, cv, 0), gid, num_segments=capacity
            )
        elif s.kind in ("min", "max"):
            sv, _ = acc_lanes[f"{s.output}$val"]
            cv, _ = acc_lanes[f"{s.output}$valid"]
            has = w & (cv > 0)
            if sv.dtype.kind == "f":
                sentinel = jnp.inf if s.kind == "min" else -jnp.inf
            else:
                sentinel = I64_MAX if s.kind == "min" else -I64_MAX
            vv = jnp.where(has, sv, sentinel)
            seg = jax.ops.segment_min if s.kind == "min" else jax.ops.segment_max
            out[f"{s.output}$val"] = seg(vv, gid, num_segments=capacity)
            out[f"{s.output}$valid"] = jax.ops.segment_sum(
                jnp.where(w, cv, 0), gid, num_segments=capacity
            )
        else:
            raise NotImplementedError(s.kind)
    return out


def finalize(
    specs: Sequence[AggSpec], accs: Dict[str, jnp.ndarray]
) -> Dict[str, Lane]:
    """Accumulators -> output lanes (SINGLE/FINAL output step)."""
    out: Dict[str, Lane] = {}
    for s in specs:
        if s.kind in ("count", "count_star"):
            c = accs[f"{s.output}$count"]
            out[s.output] = (c, jnp.ones(c.shape, bool))
        elif s.kind == "sum":
            v = accs[f"{s.output}$val"]
            cnt = accs[f"{s.output}$valid"]
            out[s.output] = (v, cnt > 0)
        elif s.kind in ("min", "max"):
            v = accs[f"{s.output}$val"]
            cnt = accs[f"{s.output}$valid"]
            zero = jnp.zeros_like(v)
            out[s.output] = (jnp.where(cnt > 0, v, zero), cnt > 0)
        elif s.kind == "avg":
            ssum = accs[f"{s.output}$sum"]
            cnt = accs[f"{s.output}$count"]
            den = jnp.maximum(cnt, 1)
            ot = s.output_type
            if ssum.dtype.kind == "f":
                v = ssum / den
            elif ot is not None and ot.name in ("double", "real"):
                # Trino: avg(integer-type) -> double
                v = ssum.astype(ot.np_dtype) / den
            elif ot is not None and ot.is_decimal and s.input_type is not None:
                # rescale sum to output scale before integer divide
                shift = 10 ** (ot.scale - s.input_type.scale)
                num = ssum * shift
                sign = jnp.sign(num)
                anum = jnp.abs(num)
                q = anum // den
                rem = anum - q * den
                v = sign * (q + (2 * rem >= den))
            else:
                v = ssum // den
            out[s.output] = (v, cnt > 0)
        else:
            raise NotImplementedError(s.kind)
    return out


def group_keys_output(
    key_lanes: Sequence[Lane],
    gid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
) -> List[Lane]:
    """Representative key values per group id (first selected row wins)."""
    n = gid.shape[0]
    first = jax.ops.segment_min(
        jnp.where(sel, jnp.arange(n, dtype=jnp.int64), n), gid,
        num_segments=capacity,
    )
    present = first < n
    safe = jnp.clip(first, 0, n - 1)
    out = []
    for v, ok in key_lanes:
        out.append((v[safe], ok[safe] & present))
    return out
