"""Group-by aggregation kernels.

Reference parity: operator/HashAggregationOperator.java:53,
operator/GroupByHash.java:29 (FlatGroupByHash/FlatHash open addressing),
operator/aggregation/ (112 aggregate function classes built on
AccumulatorCompiler bytecode accumulators),
aggregation/builder/InMemoryHashAggregationBuilder.java:50.

TPU-first redesign — hash tables with random scatter are hostile to the MXU/
VPU, so grouping uses two strategies (SURVEY §7 "sort-or-scatter group-by"):

  1. direct: group keys that are dictionary codes / small ints map to a
     dense group id by mixed-radix combination; accumulators are
     jax.ops.segment_sum over a static group capacity.  This is the analog
     of the reference's BigintGroupByHash fast path and covers low-
     cardinality group-bys (TPC-H Q1: 2x2 codes -> 6 ids).

  2. hash-sort: rows sorted by a salted 64-bit locator of the key tuple
     (single-operand sort — multi-key comparators explode XLA:TPU
     compile time), adjacent rows exactly verified on the real columns,
     detected collisions re-run under a fresh salt (never probabilistic),
     then the same segment accumulators.

Group capacity is static per compilation; the kernel returns the true group
count so the host can recompile with a larger capacity when exceeded
(the "recompile-on-bucket-change" idiom replacing FlatHash rehashing).

Aggregation steps mirror AggregationNode.Step (plan/AggregationNode.java:346):
PARTIAL produces accumulator columns keyed by group; FINAL re-groups partial
rows and merges accumulators — the same kernel pair handles both, which is
also the distributed merge path (all-gather partials -> final, SURVEY §2.2).

Aggregate function families (reference operator/aggregation/*):
  count/count_star/count_if, sum, min, max, avg          — basic
  var_pop/var_samp/stddev_pop/stddev_samp (+aliases)     — 2nd moments
  covar_pop/covar_samp/corr/regr_slope/regr_intercept    — binary moments
  geometric_mean                                          — log-sum
  bool_and/bool_or (every)                                — boolean
  bitwise_and_agg/bitwise_or_agg/bitwise_xor_agg          — bit-plane kernels
  checksum                                                — order-independent
  arbitrary (any_value)                                   — first non-null
  min_by/max_by                                           — argmin/argmax
  approx_distinct   — exact at SINGLE step; HLL sketch PARTIAL/FINAL
  approx_percentile — exact at SINGLE step; k-min-hash sample sketch
  array_agg/map_agg/listagg — host-staged per-group dictionaries

NULL semantics: a NULL key is its own group (tracked via the validity bit as
an extra radix/sort key); sum/min/max ignore NULL inputs and return NULL for
empty groups; count counts non-NULL only.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..expr.lower import Lane

I64_MAX = 2**62  # python int (see ops/int128.py const-arg note)

# kinds whose accumulators are 2nd-moment sums over one input
MOMENT_KINDS = ("var_samp", "var_pop", "stddev_samp", "stddev_pop")
# kinds whose accumulators are moment sums over two inputs (y, x) —
# argument order follows the reference (e.g. regr_slope(y, x))
BINARY_MOMENT_KINDS = (
    "covar_pop", "covar_samp", "corr", "regr_slope", "regr_intercept",
)
BITWISE_KINDS = ("bitwise_and_agg", "bitwise_or_agg", "bitwise_xor_agg")
# kinds that cannot be split into PARTIAL/FINAL (computed at SINGLE step
# from raw rows; the planner must not push them through exchanges).
# approx_distinct / approx_percentile left this list in round 2: at
# SINGLE step they stay exact, but PARTIAL/FINAL ship mergeable sketch
# state (ops/sketches.py: HLL registers / k-min-hash samples), the
# reference's HyperLogLog + digest accumulator design.
# array_agg/map_agg/listagg build variable-length host dictionaries per
# group (host-staged, like UNNEST): raw rows must be colocated
NON_DECOMPOSABLE = ("array_agg", "map_agg", "listagg")
HOST_STAGED_KINDS = ("array_agg", "map_agg", "listagg")
SKETCHED_KINDS = ("approx_distinct", "approx_percentile")

TWO_ARG_KINDS = ("min_by", "max_by") + BINARY_MOMENT_KINDS


def _sum_overflow_flag(vv, gid, cap):
    """int64 accumulators wrap silently; this flags any per-group sum
    whose magnitude approaches the wrap point so the query FAILS LOUDLY
    until decimal(38) storage exists.  Two stages so the safe common case
    is ~free: a scalar sum(|v|) gate (an upper bound on EVERY group's
    |sum|), and only when it fires, a per-group f64 shadow under lax.cond
    (compiled both ways, executed only on the hot side; f64 error
    ~1e-16*n cannot confuse 9.0e18 with the 9.22e18 wrap point)."""
    gate = (
        jnp.sum(jnp.abs(vv).astype(jnp.float64)) > 9.0e18
    )

    def precise():
        shadow = _seg_sum(vv.astype(jnp.float64), gid, cap)
        return jnp.sum(jnp.abs(shadow) > 9.0e18).astype(jnp.int64)

    return jax.lax.cond(
        gate, precise, lambda: jnp.zeros((), dtype=jnp.int64)
    )


def _merge_overflow_check(vals, w, gid, cap, overflow_flags):
    """Shadow re-merge of partial int sums: flags a FINAL-side wrap
    (partials fine per worker, total beyond int64)."""
    if overflow_flags is None or jnp.issubdtype(vals.dtype, jnp.floating):
        return
    overflow_flags.append(
        _sum_overflow_flag(jnp.where(w, vals, 0), gid, cap)
    )


def _sum_could_overflow(nrows: int, input_type) -> bool:
    """Static filter for the shadow overflow check: can nrows values
    of this type exceed int64?  (decimal(p,s) raw values < 10^p)."""
    digits = (
        input_type.precision
        if input_type is not None and input_type.is_decimal
        else 19
    )
    return nrows * (10.0 ** digits) > 9.0e18


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate function instance (AggregatorFactory analog)."""

    kind: str
    input: Optional[str]  # input column name (None for count_star)
    output: str
    input_type: Optional[T.Type] = None
    output_type: Optional[T.Type] = None
    distinct: bool = False
    input2: Optional[str] = None  # second arg (min_by/max_by/corr/...)
    input2_type: Optional[T.Type] = None
    param: Optional[float] = None  # constant parameter (approx_percentile)

    @property
    def _wide_sum(self) -> bool:
        """Wide (two-limb) chunked accumulation: any decimal sum/avg —
        sum outputs are typed decimal(38,s) (Int128 accumulator analog,
        spi/type/Int128Math.java), so the state is four 32-bit chunk
        sums that merge by plain addition (psum-able)."""
        from . import wide_decimal as wd

        if self.kind == "sum":
            return wd.is_wide_type(self.output_type)
        if self.kind == "avg":
            return (
                self.input_type is not None
                and self.input_type.is_decimal
                and self.output_type is not None
                and self.output_type.is_decimal
            )
        return False

    @property
    def accumulator_names(self) -> List[str]:
        o = self.output
        if self.kind == "avg":
            if self._wide_sum:
                return [f"{o}$c0", f"{o}$c1", f"{o}$c2", f"{o}$c3",
                        f"{o}$count"]
            return [f"{o}$sum", f"{o}$count"]
        if self.kind == "sum" and self._wide_sum:
            return [f"{o}$c0", f"{o}$c1", f"{o}$c2", f"{o}$c3",
                    f"{o}$valid"]
        if self.kind in ("sum", "min", "max"):
            return [f"{o}$val", f"{o}$valid"]
        if self.kind in MOMENT_KINDS:
            return [f"{o}$sum", f"{o}$sumsq", f"{o}$count"]
        if self.kind == "geometric_mean":
            return [f"{o}$sumlog", f"{o}$count"]
        if self.kind in BINARY_MOMENT_KINDS:
            return [f"{o}$sy", f"{o}$sx", f"{o}$sxy", f"{o}$sxx",
                    f"{o}$syy", f"{o}$n"]
        if self.kind == "approx_distinct":
            from . import sketches

            return [f"{o}$hll{i}" for i in range(sketches.HLL_LANES)]
        if self.kind == "approx_percentile":
            from . import sketches

            K = sketches.KMV_K
            return (
                [f"{o}$pv{i}" for i in range(K)]
                + [f"{o}$ph{i}" for i in range(K)]
                + [f"{o}$pmin", f"{o}$pmax"]
            )
        if self.kind in HOST_STAGED_KINDS:
            return [f"{o}$val", f"{o}$valid"]  # host-staged; not shipped
        if self.kind in ("bool_and", "bool_or", "checksum",
                         "arbitrary") or self.kind in BITWISE_KINDS:
            return [f"{o}$val", f"{o}$valid"]
        if self.kind in ("min_by", "max_by"):
            return [f"{o}$val", f"{o}$key", f"{o}$valid", f"{o}$has"]
        # count / count_star / count_if / approx_distinct
        return [f"{o}$count"]

    def psum_kind(self, name: str) -> Optional[str]:
        """How to merge this accumulator across mesh devices with a single
        collective: 'sum' | 'min' | 'max', or None when a collective cannot
        merge it (the executor must fall back to the gather+merge path)."""
        if self.kind in ("min", "max") and name.endswith("$val"):
            from . import wide_decimal as wd

            if wd.is_wide_type(self.output_type):
                # per-limb min/max is not lexicographic 128-bit min/max
                return None
            return self.kind
        if self.kind == "bool_and" and name.endswith("$val"):
            return "min"
        if self.kind == "bool_or" and name.endswith("$val"):
            return "max"
        if self.kind in ("arbitrary", "min_by", "max_by") or (
            self.kind in BITWISE_KINDS
        ):
            if not (name.endswith("$valid") or name.endswith("$has")
                    or name.endswith("$count")):
                return None
        if self.kind in SKETCHED_KINDS:
            # packed registers / sample slots need unpack-style merges
            # (gather path), not a single collective
            return None
        return "sum"


def direct_group_ids(
    key_lanes: Sequence[Lane], domains: Sequence[int]
) -> Tuple[jnp.ndarray, int]:
    """Mixed-radix dense group id from small-domain keys.

    Each key contributes radix (domain+1): slot `domain` encodes NULL.
    Returns (gid array, capacity).
    """
    gid = None
    cap = 1
    for (v, ok), dom in zip(key_lanes, domains):
        radix = dom + 1
        code = jnp.where(ok, jnp.clip(v.astype(jnp.int64), 0, dom - 1), dom)
        gid = code if gid is None else gid * radix + code
        cap *= radix
    return gid, cap


# >int64 bit patterns must wrap in jnp.uint64(...) AT USE (trace-time
# literal); raw python ints overflow the default int64 weak promotion
_GOLDEN = 0x9E3779B97F4A7C15
_SALT_C = 0x632BE59BD9B4E019


def _exp2i_pair(e: jnp.ndarray):
    """Exact 2^e for integer |e| <= 1046, as TWO f64 factors (apply
    sequentially to stay in range).  Built by binary factorization from
    exact power-of-two constants — no ldexp/exp2 primitive is trusted,
    since XLA:TPU's x64 rewrite lacks ldexp/frexp/64-bit bitcasts and
    library exp2 makes no exactness promise."""
    half = e // 2
    rest = e - half

    def pow_part(k):
        r = jnp.ones(k.shape, dtype=jnp.float64)
        a = jnp.abs(k)
        for j in range(10):  # covers |k| <= 1023
            c = jnp.where(
                k >= 0, jnp.float64(2.0 ** (1 << j)),
                jnp.float64(2.0 ** -(1 << j)),
            )
            r = r * jnp.where((a >> j) & 1 == 1, c, jnp.float64(1.0))
        return r

    return pow_part(half), pow_part(rest)


def f64_order_bits(v: jnp.ndarray) -> jnp.ndarray:
    """IEEE-754-equivalent uint64 for doubles, built ARITHMETICALLY
    because bitcast f64<->u64 (and frexp/ldexp) are unimplemented in
    XLA:TPU's x64 rewrite.  Exponent comes from a log2 estimate corrected
    by exact comparisons; the mantissa is extracted with exact
    power-of-two scaling, so the result EQUALS the IEEE bit pattern:
    injective (collision-verify soundness) and order-preserving, with NaN
    above +inf (Trino's NaN-largest rule).  The result is the classic
    radix-sortable float transform of that pattern."""
    v = v.astype(jnp.float64)
    av = jnp.abs(v)
    # normal path: av = m * 2^e0 with m in [1, 2)
    e0 = jnp.clip(
        jnp.floor(jnp.log2(jnp.where(av > 0, av, 1.0))), -1022.0, 1023.0
    ).astype(jnp.int32)
    s1, s2 = _exp2i_pair(-e0)
    m = av * s1 * s2
    for _ in range(2):  # log2 may misbin by one near boundaries
        big = m >= 2.0
        m = jnp.where(big, m * 0.5, m)
        e0 = e0 + big.astype(jnp.int32)
        small = (m < 1.0) & (m > 0)
        m = jnp.where(small, m * 2.0, m)
        e0 = e0 - small.astype(jnp.int32)
    safe_m = jnp.clip(m, 1.0, 2.0 - 2.0**-52)
    m_int = ((safe_m - 1.0) * jnp.float64(2.0**52)).astype(jnp.uint64)
    E = jnp.clip(e0 + 1023, 1, 2046).astype(jnp.uint64)
    bits = (E << jnp.uint64(52)) | m_int
    # subnormals, -0 and +0 all encode as 0: XLA arithmetic/comparisons
    # flush subnormals (DAZ) — verified on BOTH the TPU and CPU backends
    # ((5e-324 == 0.0) is True, (5e-324 != 0) is False in-engine) — so
    # one shared encoding is exactly consistent with the comparison
    # semantics the sort/verify kernels use
    tiny = av < jnp.float64(2.2250738585072014e-308)
    bits = jnp.where(tiny, jnp.uint64(0), bits)
    bits = jnp.where(jnp.isinf(av), jnp.uint64(0x7FF0000000000000), bits)
    bits = jnp.where(jnp.isnan(v), jnp.uint64(0x7FF8000000000000), bits)
    neg = (v < 0) & ~jnp.isnan(v)
    pattern = bits | (neg.astype(jnp.uint64) << jnp.uint64(63))
    # total order: flip all bits for negatives, set the sign bit for
    # non-negatives (the classic radix-sortable float transform)
    return jnp.where(neg, ~pattern, pattern | jnp.uint64(1 << 63))


def _key_bits(v: jnp.ndarray) -> jnp.ndarray:
    """Key column as uint64 bit material: floats get an injective
    order-preserving arithmetic encoding (no f64 bitcast on TPU), so
    distinct values never merge before hashing and NaN has a stable
    identity for both hashing and exact verification."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        return f64_order_bits(v)
    return v.astype(jnp.uint64)


def _key_bit_lanes(v: jnp.ndarray):
    """Key column as one or two uint64 bit-material lanes (wide decimals
    contribute each limb as its own hashing/verification round)."""
    if v.ndim == 2:
        return [v[:, 0].astype(jnp.uint64), v[:, 1].astype(jnp.uint64)]
    return [_key_bits(v)]


def _group_hash(key_lanes: Sequence[Lane], salt: int) -> jnp.ndarray:
    """Salted 64-bit key-tuple locator.  The NULL flag is mixed as its own
    round (not as a sentinel value), so `NULL` and any real value can never
    permanently collide — a salt change re-randomizes every collision."""
    n = key_lanes[0][0].shape[0]
    h = jnp.full(n, jnp.uint64(salt * 2 + 1) * jnp.uint64(_GOLDEN), dtype=jnp.uint64)
    for v, ok in key_lanes:
        h = h * jnp.uint64(_GOLDEN) + ok.astype(jnp.uint64) + jnp.uint64(_SALT_C)
        h = h ^ (h >> jnp.uint64(31))
        for bits in _key_bit_lanes(v):
            h = h * jnp.uint64(_GOLDEN) + jnp.where(ok, bits, jnp.uint64(0))
            h = h ^ (h >> jnp.uint64(29))
    return (h % jnp.uint64(2**61)).astype(jnp.int64)


def sort_group_ids(
    key_lanes: Sequence[Lane],
    sel: jnp.ndarray,
    capacity: int,
    salt: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Hash-sort grouping: returns (perm, gid_sorted, ngroups, collisions).

    perm reorders rows so equal keys are adjacent (unselected rows last);
    gid_sorted[i] is the group id of sorted row i (unselected rows get
    capacity-1 but are excluded by weight later).

    TPU-first design note: a lexicographic multi-key `lax.sort` compiles a
    (1+2k)-operand comparator whose XLA:TPU compile time explodes with k
    (~190s for k=3 at 8M rows vs ~50s for one key).  Instead rows sort by
    ONE salted 64-bit locator hash of the key tuple, and adjacent rows in
    the same hash run are verified equal on the real key columns — the
    `collisions` output counts mismatches (probability ~n²/2⁻⁶⁴) and the
    executor re-runs with a fresh salt when it is ever nonzero, so results
    are exact, never probabilistic (same protocol as the join locators)."""
    n = key_lanes[0][0].shape[0]
    hk = _group_hash(key_lanes, salt)
    key = jnp.where(sel, hk, jnp.int64(2**61))  # dead rows sort last
    sorted_key, perm = jax.lax.sort(
        (key, jnp.arange(n, dtype=jnp.int64)), num_keys=1
    )
    sel_sorted = sorted_key < jnp.int64(2**61)
    diff = jnp.concatenate(
        [jnp.ones(1, bool), sorted_key[1:] != sorted_key[:-1]]
    )
    boundary = diff & sel_sorted
    # exact adjacent verification (PagesHashStrategy positionEquals analog)
    prev = jnp.concatenate([perm[:1], perm[:-1]])
    same_run = (~diff) & sel_sorted
    all_eq = jnp.ones(n, dtype=bool)
    for v, ok in key_lanes:
        okp, okq = ok[perm], ok[prev]
        vals_eq = jnp.ones(n, dtype=bool)
        for bits in _key_bit_lanes(v):
            vals_eq = vals_eq & (bits[perm] == bits[prev])
        lane_eq = (okp == okq) & (~okp | vals_eq)
        all_eq = all_eq & lane_eq
    collisions = jnp.sum(same_run & ~all_eq)
    gid = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    ngroups = boundary.sum()
    gid = jnp.where(sel_sorted, jnp.clip(gid, 0, capacity - 1), capacity - 1)
    return perm, gid, ngroups, collisions


def distinct_first_mask(
    gid: jnp.ndarray, lane: Lane, live: jnp.ndarray
) -> jnp.ndarray:
    """First-occurrence mask per (group, value) over live rows, returned
    in the CALLER's row order (MarkDistinctOperator analog,
    /root/reference/core/trino-main/src/main/java/io/trino/operator/
    MarkDistinctOperator.java:34 — but as one sort by (liveness, gid,
    value-bits) + adjacent-first flags + an inverse-permutation scatter,
    instead of a row-at-a-time hash table).  Any aggregate then runs its
    NORMAL accumulator over `live & mask` — sum/avg/stddev(DISTINCT) and
    multi-distinct all reduce to this one mask per (agg, input) pair
    (DistinctAccumulatorFactory.java:36)."""
    v, _ok = lane
    n = gid.shape[0]
    bit_lanes = list(_key_bit_lanes(v))
    dead = jnp.logical_not(live)
    ops = (dead, gid, *bit_lanes, jnp.arange(n, dtype=jnp.int64))
    res = jax.lax.sort(ops, num_keys=2 + len(bit_lanes))
    d2, g2 = res[0], res[1]
    perm = res[-1]
    neq = g2[1:] != g2[:-1]
    for b in res[2:-1]:
        neq = neq | (b[1:] != b[:-1])
    first = jnp.concatenate([jnp.ones(1, bool), neq]) & jnp.logical_not(d2)
    # perm is a permutation (unique indices): one n-sized scatter back
    return jnp.zeros(n, dtype=bool).at[perm].set(first)


# DISTINCT is semantically a no-op for these kinds (duplicates cannot
# change an extremum / boolean fold / arbitrary pick)
_DISTINCT_NOOP = ("min", "max", "bool_and", "bool_or", "arbitrary",
                  "approx_distinct")
# kinds whose accumulators correctly consume a dedup-refined live mask
_DISTINCT_MASKED = ("sum", "avg", "count_if", "geometric_mean") + MOMENT_KINDS


def distinct_count(
    gid: jnp.ndarray, lane: Lane, sel: jnp.ndarray, capacity: int
) -> jnp.ndarray:
    """count(DISTINCT x) per group: sort by (gid, x), count first
    occurrences (MarkDistinctOperator + count, in one sort)."""
    v, ok = lane
    live = sel & ok
    n = gid.shape[0]
    vv = v.astype(jnp.int64) if v.dtype.kind in ("i", "u", "b") else v
    dead = jnp.logical_not(live)
    # dead rows sort last; within live rows, equal (gid, value) adjacent
    sorted_ops = jax.lax.sort(
        (dead, gid, vv, jnp.arange(n, dtype=jnp.int64)), num_keys=3
    )
    d2, g2, v2, perm = sorted_ops
    live2 = jnp.logical_not(d2)
    first = jnp.concatenate(
        [jnp.ones(1, bool), (g2[1:] != g2[:-1]) | (v2[1:] != v2[:-1])]
    )
    flags = (first & live2).astype(jnp.int64)
    return jax.ops.segment_sum(flags, jnp.clip(g2, 0, capacity - 1),
                               num_segments=capacity)


# Scatter-add is slow on TPU (no native scatter unit): for small group
# capacities a one-hot masked reduction is several times faster (measured
# ~0.15s vs ~0.6s for 6M rows x 12 groups on v5e), so segment reductions
# pick their implementation by capacity and backend.
_SMALL_SEG_CAP = 32


def _use_masked(cap: int) -> bool:
    try:
        return cap <= _SMALL_SEG_CAP and jax.default_backend() == "tpu"
    except Exception:
        return False


def _seg_sum(v, gid, cap):
    if _use_masked(cap) and v.ndim == 1:
        m = gid[None, :] == jnp.arange(cap, dtype=gid.dtype)[:, None]
        zero = jnp.zeros((), dtype=v.dtype)
        return jnp.sum(jnp.where(m, v[None, :], zero), axis=1)
    return jax.ops.segment_sum(v, gid, num_segments=cap)


def _seg_count(mask, gid, cap):
    """Per-group count of a boolean mask.  Counts are the pallas
    single-f32-plane case (ops/pallas_kernels.grouped_count, ~14x the XLA
    lowering on TPU at SF1 shapes); general int64 sums measured SLOWER in
    pallas (int ops lack VPU MACs) and stay on _seg_sum."""
    from . import pallas_kernels

    ps = pallas_kernels.seg_count_maybe(mask, gid, cap)
    if ps is not None:
        return ps
    return _seg_sum(mask.astype(jnp.int64), gid, cap)


def _seg_min(v, gid, cap):
    if _use_masked(cap) and v.ndim == 1:
        if v.dtype.kind == "f":
            sent = jnp.asarray(jnp.inf, dtype=v.dtype)
        else:
            sent = jnp.asarray(jnp.iinfo(v.dtype).max, dtype=v.dtype)
        m = gid[None, :] == jnp.arange(cap, dtype=gid.dtype)[:, None]
        return jnp.min(jnp.where(m, v[None, :], sent), axis=1)
    return jax.ops.segment_min(v, gid, num_segments=cap)


def _seg_max(v, gid, cap):
    if _use_masked(cap) and v.ndim == 1:
        if v.dtype.kind == "f":
            sent = jnp.asarray(-jnp.inf, dtype=v.dtype)
        else:
            sent = jnp.asarray(jnp.iinfo(v.dtype).min, dtype=v.dtype)
        m = gid[None, :] == jnp.arange(cap, dtype=gid.dtype)[:, None]
        return jnp.max(jnp.where(m, v[None, :], sent), axis=1)
    return jax.ops.segment_max(v, gid, num_segments=cap)


def _seg_minmax_wide(v, live, gid, cap, take_min: bool):
    """Lexicographic segment min/max of a wide (two-limb) decimal lane:
    extreme high limb first, then the extreme unsigned low limb among
    rows whose high limb attains it (two segment passes, both exact).

    Sentinels are the TRUE int64 extremes (not the engine's 2^62
    I64_MAX): limbs span the full 64-bit domain."""
    from . import wide_decimal as wd

    lo, hi = wd.limbs(v)
    lo_u = lo ^ jnp.int64(-0x8000000000000000)  # unsigned order, signed domain
    seg = _seg_min if take_min else _seg_max
    sent = (
        jnp.int64(0x7FFFFFFFFFFFFFFF)
        if take_min
        else jnp.int64(-0x8000000000000000)
    )
    hi_ext = seg(jnp.where(live, hi, sent), gid, cap)
    on_ext = live & (hi == hi_ext[gid])
    lo_ext = seg(jnp.where(on_ext, lo_u, sent), gid, cap)
    return wd.make_wide(
        lo_ext ^ jnp.int64(-0x8000000000000000), hi_ext
    )


def _splitmix64(v: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — order-independent per-value hash for checksum.
    (The reference's checksum xors XxHash64 values: aggregation/ChecksumAggregationFunction;
    we sum splitmix64 hashes, equally order-independent.)"""
    z = v.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> 27)) * jnp.uint64(0x94D049BB133111EB)
    z = z ^ (z >> 31)
    return z.astype(jnp.int64)


def _segment_bitwise(vals, live, gid, cap, op: str, live_cnt=None):
    """Per-group bitwise and/or/xor via one 2-D segment_sum over bit planes.

    No segment_and/or exists in XLA; instead decompose into a [n, 64] 0/1
    matrix, segment-sum it to per-group bit counts [cap, 64], then
    AND = (count == group_size), OR = (count > 0), XOR = (count & 1).
    """
    # created at trace time (a module-level arange would become a hidden
    # const arg — see ops/int128.py const-arg note)
    bit_shifts = jnp.arange(64, dtype=jnp.uint64)
    u = vals.astype(jnp.uint64)
    bits = ((u[:, None] >> bit_shifts[None, :]) & jnp.uint64(1)).astype(
        jnp.int32
    )
    bits = jnp.where(live[:, None], bits, 0)
    sums = _seg_sum(bits, gid, cap)  # [cap, 64]
    if live_cnt is None:
        live_cnt = _seg_sum(live.astype(jnp.int32), gid, cap)
    if op == "or":
        outbits = (sums > 0)
    elif op == "and":
        outbits = (sums == live_cnt[:, None]) & (live_cnt[:, None] > 0)
    else:  # xor
        outbits = (sums & 1) == 1
    vals64 = (outbits.astype(jnp.uint64) << bit_shifts[None, :]).sum(
        axis=1, dtype=jnp.uint64
    )
    return vals64.astype(jnp.int64)


def _first_by_key(xlane, key, live, gid, cap, take_min: bool):
    """Per-group x-value at the min/max key row (min_by/max_by kernel).

    Two-pass argmin: (1) segment extremum of the key, (2) first row index
    whose key equals the extremum, (3) gather x there."""
    x, xok = xlane
    n = gid.shape[0]
    if key.dtype.kind == "f":
        sentinel = jnp.inf if take_min else -jnp.inf
        kv = jnp.where(live, key, sentinel)
    else:
        sentinel = I64_MAX if take_min else -I64_MAX
        kv = jnp.where(live, key.astype(jnp.int64), sentinel)
    seg = _seg_min if take_min else _seg_max
    extremum = seg(kv, gid, cap)
    cand = live & (kv == extremum[gid])
    ridx = _seg_min(
        jnp.where(cand, jnp.arange(n, dtype=jnp.int64), n), gid, cap
    )
    has = ridx < n
    safe = jnp.clip(ridx, 0, n - 1)
    xv = x[safe]
    xvalid = xok[safe] & has
    zero = jnp.zeros_like(extremum)
    return (
        jnp.where(has, xv, jnp.zeros_like(xv)),
        jnp.where(has, extremum, zero),
        xvalid,
        has,
    )


def _percentile(lane: Lane, sel, gid, cap, frac: float):
    """Exact per-group percentile by sort (the engine's approx_percentile:
    zero-error flavor of the reference's qdigest-based one)."""
    v, ok = lane
    live = sel & ok
    n = gid.shape[0]
    dead = jnp.logical_not(live)
    vv = v.astype(jnp.int64) if v.dtype.kind in ("i", "u", "b") else v
    d2, g2, v2 = jax.lax.sort((dead, gid, vv), num_keys=3)
    live2 = jnp.logical_not(d2)
    cnt = _seg_sum(live2.astype(jnp.int64), jnp.clip(g2, 0, cap - 1), cap)
    start = jnp.cumsum(cnt) - cnt  # live rows sort before dead ones per gid?
    # live rows of group g occupy a contiguous run; compute each sorted row's
    # rank within its group
    g2c = jnp.clip(g2, 0, cap - 1)
    rank = jnp.arange(n, dtype=jnp.int64) - start[g2c]
    target = jnp.clip(
        jnp.floor(frac * (cnt - 1).astype(jnp.float64) + 0.5).astype(jnp.int64),
        0,
        jnp.maximum(cnt - 1, 0),
    )
    pick = live2 & (rank == target[g2c])
    if v2.dtype.kind == "f":
        out = _seg_max(jnp.where(pick, v2, -jnp.inf), g2c, cap)
        out = jnp.where(cnt > 0, out, 0.0)
    else:
        out = _seg_max(jnp.where(pick, v2, -I64_MAX), g2c, cap)
        out = jnp.where(cnt > 0, out, 0)
    return out.astype(v.dtype) if v.dtype.kind != "f" else out, cnt > 0


def _as_double(v: jnp.ndarray, t: Optional[T.Type]) -> jnp.ndarray:
    """Numeric lane -> float64, unscaling fixed-point decimals."""
    if t is None:
        return v.astype(jnp.float64)
    from ..expr.functions import to_double

    return to_double(v, t)


def _moment_sums(v, live, gid, cap, in_t):
    x = jnp.where(live, _as_double(v, in_t), 0.0)
    return (
        _seg_sum(x, gid, cap),
        _seg_sum(x * x, gid, cap),
        _seg_sum(live.astype(jnp.int64), gid, cap),
    )


class SortedSegments:
    """Scatter-free grouped reductions over a SORTED gid lane (the
    hash-sort grouping path: rows arrive permuted so equal groups are
    adjacent, gid non-decreasing).

    XLA:TPU scatter runs ~16M updates/s regardless of sortedness hints
    (MICRO_group.json), so at capacities beyond the masked-matrix range
    every accumulator cost ~0.5s at SF1.  Sorted runs instead admit:
      - ONE extra single-key sort (merge_rank of arange(cap) into the
        sorted gids) shared by all aggregates, giving each group's
        [start, end) row range, then
      - per-aggregate cumsum + two cap-sized gathers (sums/counts) or a
        segmented scan + end-gather (min/max) — all bandwidth-bound.
    """

    def __init__(self, gid: jnp.ndarray, cap: int):
        from .join import merge_rank

        self.gid = gid
        self.cap = cap
        self.n = gid.shape[0]
        probe = jnp.arange(cap, dtype=jnp.int64)
        self.starts = merge_rank(gid, probe, side="left")
        self.ends = merge_rank(gid, probe, side="right")
        self.counts_all = self.ends - self.starts  # incl. non-live rows

    def _range_diff(self, cs: jnp.ndarray) -> jnp.ndarray:
        """cs = inclusive prefix over rows -> per-group range totals."""
        zero = jnp.zeros(1, dtype=cs.dtype)
        cs0 = jnp.concatenate([zero, cs])  # cs0[i] = sum of rows < i
        return cs0[self.ends] - cs0[self.starts]

    def sum(self, v: jnp.ndarray) -> jnp.ndarray:
        return self._range_diff(jnp.cumsum(v))

    def count(self, mask: jnp.ndarray) -> jnp.ndarray:
        return self._range_diff(jnp.cumsum(mask.astype(jnp.int64)))

    def _scan_extreme(self, v: jnp.ndarray, take_min: bool) -> jnp.ndarray:
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), self.gid[1:] != self.gid[:-1]]
        )
        op = jnp.minimum if take_min else jnp.maximum

        def combine(a, b):
            f1, v1 = a
            f2, v2 = b
            return (f1 | f2, jnp.where(f2, v2, op(v1, v2)))

        _, run = jax.lax.associative_scan(combine, (boundary, v))
        # group extremum lands at each run's LAST row = ends-1
        last = jnp.clip(self.ends - 1, 0, self.n - 1)
        return run[last]

    def min(self, v: jnp.ndarray) -> jnp.ndarray:
        return self._scan_extreme(v, True)

    def max(self, v: jnp.ndarray) -> jnp.ndarray:
        return self._scan_extreme(v, False)


# aggregate kinds the SortedSegments fast path covers; others fall back
# to the generic segment ops
SORTED_FAST_KINDS = ("sum", "avg", "count", "count_star", "count_if",
                     "min", "max")


def accumulate(
    specs: Sequence[AggSpec],
    lanes: Dict[str, Lane],
    gid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
    step: str = "single",
    overflow_flags: Optional[list] = None,
    wide_flags: Optional[list] = None,
    force_wide: bool = True,
    seg: Optional["SortedSegments"] = None,
) -> Dict[str, jnp.ndarray]:
    """Compute accumulator arrays (shape [capacity]) per spec.

    step='single' keeps approx_* exact (sort-based); step='partial'
    emits mergeable sketch state instead (ops/sketches.py), the
    decomposable PARTIAL/FINAL form shipped across exchanges.

    wide_flags/force_wide drive the decimal(38) sum fast path: callers
    wired into the executor retry ladder pass the lowering's wide-mul
    flag list and its force_wide_mul state; unwired callers keep the
    always-exact (slower) chunked default via force_wide=True."""
    out: Dict[str, jnp.ndarray] = {}
    cap = capacity
    # one dedup mask per DISTINCT input column, shared across specs
    # (sum(DISTINCT x) + avg(DISTINCT x) sort once, not twice)
    distinct_masks: Dict[str, jnp.ndarray] = {}

    # Scatter-free sorted-run reductions when the caller's gid is sorted
    # (hash-sort grouping).  Integer-only for sums: float range-diffs
    # would trade scatter cost for cancellation error.
    def seg_cnt(mask):
        if seg is not None:
            return seg.count(mask)
        return _seg_count(mask, gid, cap)

    def seg_isum(vv):
        if seg is not None and vv.dtype.kind != "f":
            return seg.sum(vv)
        return _seg_sum(vv, gid, cap)

    def seg_ext(vv, take_min):
        if seg is not None and vv.dtype.kind != "f":
            return seg.min(vv) if take_min else seg.max(vv)
        return (_seg_min if take_min else _seg_max)(vv, gid, cap)

    for s in specs:
        o = s.output
        if getattr(s, "distinct", False) and s.kind == "count":
            # count(DISTINCT x): specialized one-sort path (the mask
            # route would spend an extra scatter for the same answer)
            out[f"{o}$count"] = distinct_count(gid, lanes[s.input], sel, cap)
            continue
        if s.kind == "count_star":
            out[f"{o}$count"] = seg_cnt(sel)
            continue
        v, ok = lanes[s.input]
        live = sel & ok
        if getattr(s, "distinct", False) and s.kind not in _DISTINCT_NOOP:
            if s.kind not in _DISTINCT_MASKED:
                raise NotImplementedError(
                    f"{s.kind}(DISTINCT) not supported"
                )
            if step != "single":
                raise NotImplementedError(
                    "DISTINCT aggregates are non-decomposable: the "
                    "planner must not split them PARTIAL/FINAL"
                )
            m = distinct_masks.get(s.input)
            if m is None:
                m = distinct_masks[s.input] = distinct_first_mask(
                    gid, (v, ok), live
                )
            live = live & m
        if s.kind == "count":
            out[f"{o}$count"] = seg_cnt(live)
        elif s.kind == "count_if":
            hit = live & (v.astype(bool))
            out[f"{o}$count"] = seg_cnt(hit)
        elif s.kind == "approx_distinct":
            if step == "single":
                out[f"{o}$count"] = distinct_count(gid, (v, ok), sel, cap)
            else:
                from . import sketches

                packed = sketches.hll_accumulate(
                    _key_bits(v), live, gid, cap
                )
                for i, arr in packed.items():
                    out[f"{o}$hll{i}"] = arr
        elif s.kind in ("sum", "avg"):
            cnt = seg_cnt(live)
            if s._wide_sum:
                # exact 128-bit decimal sum with a NARROW fast path: the
                # accumulator SCHEMA is always four 32-bit chunk lanes
                # ($c0..$c3, stable across retraces), but when the input
                # is one limb and wide math is not forced, the sum runs
                # as ONE int64 segment sum + a shadow overflow flag; a
                # detected wrap retraces with force_wide (the same
                # ladder as wide multiplies), where true chunked sums
                # take over.  TPC-H Q1/Q6-scale sums never trip it, so
                # exactness at decimal(38) costs ~nothing steady-state.
                from . import wide_decimal as wd

                if wd.is_wide(v) or force_wide:
                    chunks = (
                        wd.wide_row_chunks(v, live)
                        if wd.is_wide(v)
                        else wd.narrow_row_chunks(v, live)
                    )
                    cs = wd.seg_sum_chunks(chunks, gid, cap)
                else:
                    vv = jnp.where(live, v.astype(jnp.int64), 0)
                    ssum = seg_isum(vv)
                    if wide_flags is not None and _sum_could_overflow(
                        v.shape[0], s.input_type
                    ):
                        wide_flags.append(_sum_overflow_flag(vv, gid, cap))
                    cs = wd.normalize_chunks([
                        ssum & 0xFFFFFFFF, ssum >> jnp.int64(32),
                        jnp.zeros_like(ssum), jnp.zeros_like(ssum),
                    ])
                for i, c in enumerate(cs):
                    out[f"{o}$c{i}"] = c
                out[f"{o}$valid" if s.kind == "sum" else f"{o}$count"] = cnt
                continue
            if v.dtype.kind == "f":
                vv = jnp.where(live, v, 0.0)
            else:
                vv = jnp.where(live, v.astype(jnp.int64), 0)
            ssum = seg_isum(vv)
            if (
                v.dtype.kind != "f"
                and overflow_flags is not None
                and _sum_could_overflow(v.shape[0], s.input_type)
            ):
                overflow_flags.append(_sum_overflow_flag(vv, gid, cap))
            if s.kind == "sum":
                out[f"{o}$val"] = ssum
                out[f"{o}$valid"] = cnt
            else:
                out[f"{o}$sum"] = ssum
                out[f"{o}$count"] = cnt
        elif s.kind in ("min", "max"):
            from . import wide_decimal as wd

            if wd.is_wide(v):
                out[f"{o}$val"] = _seg_minmax_wide(
                    v, live, gid, cap, s.kind == "min"
                )
                out[f"{o}$valid"] = _seg_count(live, gid, cap)
                continue
            if v.dtype.kind == "f":
                sentinel = jnp.inf if s.kind == "min" else -jnp.inf
                vv = jnp.where(live, v, sentinel)
            else:
                sentinel = I64_MAX if s.kind == "min" else -I64_MAX
                vv = jnp.where(live, v.astype(jnp.int64), sentinel)
            out[f"{o}$val"] = seg_ext(vv, s.kind == "min")
            out[f"{o}$valid"] = seg_cnt(live)
        elif s.kind in MOMENT_KINDS:
            sm, sq, cnt = _moment_sums(v, live, gid, cap, s.input_type)
            out[f"{o}$sum"], out[f"{o}$sumsq"], out[f"{o}$count"] = sm, sq, cnt
        elif s.kind == "geometric_mean":
            x = _as_double(v, s.input_type)
            lx = jnp.where(live & (x > 0), jnp.log(jnp.maximum(x, 1e-300)), 0.0)
            out[f"{o}$sumlog"] = _seg_sum(lx, gid, cap)
            out[f"{o}$count"] = _seg_count(live, gid, cap)
        elif s.kind in BINARY_MOMENT_KINDS:
            y, yok = lanes[s.input]
            x, xok = lanes[s.input2]
            both = sel & yok & xok
            xf = jnp.where(both, _as_double(x, s.input2_type), 0.0)
            yf = jnp.where(both, _as_double(y, s.input_type), 0.0)
            out[f"{o}$sy"] = _seg_sum(yf, gid, cap)
            out[f"{o}$sx"] = _seg_sum(xf, gid, cap)
            out[f"{o}$sxy"] = _seg_sum(xf * yf, gid, cap)
            out[f"{o}$sxx"] = _seg_sum(xf * xf, gid, cap)
            out[f"{o}$syy"] = _seg_sum(yf * yf, gid, cap)
            out[f"{o}$n"] = _seg_count(both, gid, cap)
        elif s.kind in ("bool_and", "bool_or"):
            cnt = _seg_count(live, gid, cap)
            if s.kind == "bool_and":
                vv = jnp.where(live, v.astype(jnp.int64), 1)
                out[f"{o}$val"] = _seg_min(vv, gid, cap)
            else:
                vv = jnp.where(live, v.astype(jnp.int64), 0)
                out[f"{o}$val"] = _seg_max(vv, gid, cap)
            out[f"{o}$valid"] = cnt
        elif s.kind in BITWISE_KINDS:
            op = {"bitwise_and_agg": "and", "bitwise_or_agg": "or",
                  "bitwise_xor_agg": "xor"}[s.kind]
            cnt = _seg_count(live, gid, cap)
            out[f"{o}$val"] = _segment_bitwise(
                v, live, gid, cap, op, cnt.astype(jnp.int32)
            )
            out[f"{o}$valid"] = cnt
        elif s.kind == "checksum":
            addend = jnp.where(
                ok, _splitmix64(v), jnp.int64(0x6E67_6C6C_7561)
            )
            out[f"{o}$val"] = _seg_sum(jnp.where(sel, addend, 0), gid, cap)
            out[f"{o}$valid"] = _seg_count(sel, gid, cap)
        elif s.kind == "arbitrary":
            n = gid.shape[0]
            ridx = _seg_min(
                jnp.where(live, jnp.arange(n, dtype=jnp.int64), n), gid, cap
            )
            has = ridx < n
            safe = jnp.clip(ridx, 0, n - 1)
            out[f"{o}$val"] = jnp.where(has, v[safe], jnp.zeros_like(v[safe]))
            out[f"{o}$valid"] = has.astype(jnp.int64)
        elif s.kind in ("min_by", "max_by"):
            key, kok = lanes[s.input2]
            xv, kv, xvalid, has = _first_by_key(
                (v, ok), key, sel & kok, gid, cap, s.kind == "min_by"
            )
            out[f"{o}$val"] = xv
            out[f"{o}$key"] = kv
            out[f"{o}$valid"] = xvalid.astype(jnp.int64)
            out[f"{o}$has"] = has.astype(jnp.int64)
        elif s.kind == "approx_percentile":
            if step == "single":
                val, valid = _percentile(
                    (v, ok), sel, gid, cap, float(s.param)
                )
                out[f"{o}$val"] = val
                out[f"{o}$valid"] = valid.astype(jnp.int64)
            else:
                from . import sketches

                K = sketches.KMV_K
                vals, hs = sketches.kmv_accumulate(v, live, gid, cap)
                vals2 = vals.reshape(cap, K)
                hs2 = hs.reshape(cap, K)
                for i in range(K):
                    out[f"{o}$pv{i}"] = vals2[:, i]
                    out[f"{o}$ph{i}"] = hs2[:, i]
                if v.dtype.kind == "f":
                    lo = jnp.where(live, v, jnp.inf)
                    hi = jnp.where(live, v, -jnp.inf)
                else:
                    lo = jnp.where(live, v.astype(jnp.int64), I64_MAX)
                    hi = jnp.where(live, v.astype(jnp.int64), -I64_MAX)
                out[f"{o}$pmin"] = _seg_min(lo, gid, cap)
                out[f"{o}$pmax"] = _seg_max(hi, gid, cap)
        elif s.kind in HOST_STAGED_KINDS:
            raise NotImplementedError(
                f"{s.kind} is host-staged (exec/local.py _host_agg_lanes)"
                " and cannot run inside a traced kernel (mesh path)"
            )
        else:
            raise NotImplementedError(s.kind)
    return out


def _merge_wide_chunks(s, acc_lanes, w, gid, cap, out):
    """Merge shipped wide-sum chunk columns: segment sums + one carry
    pass (chunk sums stay canonical, so cross-worker merges never
    overflow below 2^31 merged partials)."""
    from . import wide_decimal as wd

    o = s.output
    merged = wd.merge_chunk_lanes(
        [acc_lanes[f"{o}$c{i}"][0] for i in range(4)], w, gid, cap
    )
    for i, c in enumerate(merged):
        out[f"{o}$c{i}"] = c


def merge_accumulators(
    specs: Sequence[AggSpec],
    acc_lanes: Dict[str, Lane],
    gid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
    overflow_flags: Optional[list] = None,
) -> Dict[str, jnp.ndarray]:
    """FINAL step: merge partial accumulator rows grouped by gid."""
    out: Dict[str, jnp.ndarray] = {}
    cap = capacity
    w = sel

    def msum(name, zero=0):
        v, _ = acc_lanes[name]
        z = 0.0 if v.dtype.kind == "f" else zero
        out[name] = _seg_sum(jnp.where(w, v, z), gid, cap)

    for s in specs:
        o = s.output
        if s.kind == "approx_distinct":
            from . import sketches

            packed = sketches.hll_merge(
                {i: acc_lanes[f"{o}$hll{i}"][0]
                 for i in range(sketches.HLL_LANES)},
                w, gid, cap,
            )
            for i, arr in packed.items():
                out[f"{o}$hll{i}"] = arr
        elif s.kind == "approx_percentile":
            from . import sketches

            K = sketches.KMV_K
            n = gid.shape[0]
            vals = jnp.stack(
                [acc_lanes[f"{o}$pv{i}"][0] for i in range(K)], axis=1
            )
            hs = jnp.stack(
                [acc_lanes[f"{o}$ph{i}"][0] for i in range(K)], axis=1
            )
            hs = jnp.where(w[:, None], hs, sketches._H_EMPTY)
            mv, mh = sketches.kmv_merge(vals, hs, w, gid, cap)
            mv2 = mv.reshape(cap, K)
            mh2 = mh.reshape(cap, K)
            for i in range(K):
                out[f"{o}$pv{i}"] = mv2[:, i]
                out[f"{o}$ph{i}"] = mh2[:, i]
            lo, _ = acc_lanes[f"{o}$pmin"]
            hi, _ = acc_lanes[f"{o}$pmax"]
            if lo.dtype.kind == "f":
                lo = jnp.where(w, lo, jnp.inf)
                hi = jnp.where(w, hi, -jnp.inf)
            else:
                lo = jnp.where(w, lo, I64_MAX)
                hi = jnp.where(w, hi, -I64_MAX)
            out[f"{o}$pmin"] = _seg_min(lo, gid, cap)
            out[f"{o}$pmax"] = _seg_max(hi, gid, cap)
        elif s.kind in ("count", "count_star", "count_if"):
            msum(f"{o}$count")
        elif s.kind == "avg":
            if s._wide_sum:
                _merge_wide_chunks(s, acc_lanes, w, gid, cap, out)
                msum(f"{o}$count")
                continue
            msum(f"{o}$sum")
            msum(f"{o}$count")
            _merge_overflow_check(
                acc_lanes[f"{o}$sum"][0], w, gid, cap, overflow_flags
            )
        elif s.kind == "sum":
            if s._wide_sum:
                _merge_wide_chunks(s, acc_lanes, w, gid, cap, out)
                msum(f"{o}$valid")
                continue
            msum(f"{o}$val")
            msum(f"{o}$valid")
            _merge_overflow_check(
                acc_lanes[f"{o}$val"][0], w, gid, cap, overflow_flags
            )
        elif s.kind in MOMENT_KINDS:
            msum(f"{o}$sum")
            msum(f"{o}$sumsq")
            msum(f"{o}$count")
        elif s.kind == "geometric_mean":
            msum(f"{o}$sumlog")
            msum(f"{o}$count")
        elif s.kind in BINARY_MOMENT_KINDS:
            for suf in ("$sy", "$sx", "$sxy", "$sxx", "$syy", "$n"):
                msum(o + suf)
        elif s.kind in ("min", "max"):
            from . import wide_decimal as wd

            sv, _ = acc_lanes[f"{o}$val"]
            cv, _ = acc_lanes[f"{o}$valid"]
            has = w & (cv > 0)
            if wd.is_wide(sv):
                out[f"{o}$val"] = _seg_minmax_wide(
                    sv, has, gid, cap, s.kind == "min"
                )
                out[f"{o}$valid"] = _seg_sum(jnp.where(w, cv, 0), gid, cap)
                continue
            if sv.dtype.kind == "f":
                sentinel = jnp.inf if s.kind == "min" else -jnp.inf
            else:
                sentinel = I64_MAX if s.kind == "min" else -I64_MAX
            vv = jnp.where(has, sv, sentinel)
            seg = _seg_min if s.kind == "min" else _seg_max
            out[f"{o}$val"] = seg(vv, gid, cap)
            out[f"{o}$valid"] = _seg_sum(jnp.where(w, cv, 0), gid, cap)
        elif s.kind in ("bool_and", "bool_or"):
            sv, _ = acc_lanes[f"{o}$val"]
            cv, _ = acc_lanes[f"{o}$valid"]
            has = w & (cv > 0)
            if s.kind == "bool_and":
                vv = jnp.where(has, sv, 1)
                out[f"{o}$val"] = _seg_min(vv, gid, cap)
            else:
                vv = jnp.where(has, sv, 0)
                out[f"{o}$val"] = _seg_max(vv, gid, cap)
            out[f"{o}$valid"] = _seg_sum(jnp.where(w, cv, 0), gid, cap)
        elif s.kind in BITWISE_KINDS:
            sv, _ = acc_lanes[f"{o}$val"]
            cv, _ = acc_lanes[f"{o}$valid"]
            has = w & (cv > 0)
            op = {"bitwise_and_agg": "and", "bitwise_or_agg": "or",
                  "bitwise_xor_agg": "xor"}[s.kind]
            out[f"{o}$val"] = _segment_bitwise(sv, has, gid, cap, op)
            out[f"{o}$valid"] = _seg_sum(jnp.where(w, cv, 0), gid, cap)
        elif s.kind == "checksum":
            msum(f"{o}$val")
            msum(f"{o}$valid")
        elif s.kind == "arbitrary":
            sv, _ = acc_lanes[f"{o}$val"]
            cv, _ = acc_lanes[f"{o}$valid"]
            has = w & (cv > 0)
            n = gid.shape[0]
            ridx = _seg_min(
                jnp.where(has, jnp.arange(n, dtype=jnp.int64), n), gid, cap
            )
            ok2 = ridx < n
            safe = jnp.clip(ridx, 0, n - 1)
            out[f"{o}$val"] = jnp.where(ok2, sv[safe], jnp.zeros_like(sv[safe]))
            out[f"{o}$valid"] = ok2.astype(jnp.int64)
        elif s.kind in ("min_by", "max_by"):
            sv, _ = acc_lanes[f"{o}$val"]
            kv, _ = acc_lanes[f"{o}$key"]
            xval, _ = acc_lanes[f"{o}$valid"]
            hv, _ = acc_lanes[f"{o}$has"]
            has = w & (hv > 0)
            xv, kk, xvalid, has2 = _first_by_key(
                (sv, xval > 0), kv, has, gid, cap, s.kind == "min_by"
            )
            out[f"{o}$val"] = xv
            out[f"{o}$key"] = kk
            out[f"{o}$valid"] = xvalid.astype(jnp.int64)
            out[f"{o}$has"] = has2.astype(jnp.int64)
        else:
            raise NotImplementedError(s.kind)
    return out


def finalize(
    specs: Sequence[AggSpec], accs: Dict[str, jnp.ndarray]
) -> Dict[str, Lane]:
    """Accumulators -> output lanes (SINGLE/FINAL output step)."""
    out: Dict[str, Lane] = {}
    for s in specs:
        o = s.output
        if s.kind == "approx_distinct" and f"{o}$count" not in accs:
            # sketched (PARTIAL/FINAL) form: HLL estimator
            from . import sketches

            lanes = {i: accs[f"{o}$hll{i}"]
                     for i in range(sketches.HLL_LANES)}
            cap = lanes[0].shape[0]
            c = sketches.hll_cardinality(lanes, cap)
            out[o] = (c, jnp.ones(c.shape, bool))
        elif s.kind == "approx_percentile" and f"{o}$val" not in accs:
            from . import sketches

            K = sketches.KMV_K
            cap = accs[f"{o}$pmin"].shape[0]
            vals = jnp.stack(
                [accs[f"{o}$pv{i}"] for i in range(K)], axis=1
            ).reshape(-1)
            hs = jnp.stack(
                [accs[f"{o}$ph{i}"] for i in range(K)], axis=1
            ).reshape(-1)
            q = float(s.param)
            v, has = sketches.kmv_quantile(vals, hs, cap, q)
            lo = accs[f"{o}$pmin"]
            hi = accs[f"{o}$pmax"]
            # p=0 / p=1 exact; interior estimates clamp into range
            if q <= 0.0:
                v = lo
            elif q >= 1.0:
                v = hi
            else:
                v = jnp.clip(v, lo, hi)
            out[o] = (v, has)
        elif s.kind in ("count", "count_star", "count_if",
                        "approx_distinct"):
            c = accs[f"{o}$count"]
            out[o] = (c, jnp.ones(c.shape, bool))
        elif s.kind == "sum":
            if s._wide_sum:
                from . import wide_decimal as wd

                cs = wd.normalize_chunks(
                    [accs[f"{o}$c{i}"] for i in range(4)]
                )
                cnt = accs[f"{o}$valid"]
                out[o] = (wd.chunks_to_wide(cs), cnt > 0)
                continue
            v = accs[f"{o}$val"]
            cnt = accs[f"{o}$valid"]
            out[o] = (v, cnt > 0)
        elif s.kind in ("min", "max"):
            from . import wide_decimal as wd

            v = accs[f"{o}$val"]
            cnt = accs[f"{o}$valid"]
            zero = jnp.zeros_like(v)
            has = cnt > 0
            if wd.is_wide(v):
                has = has[:, None]
            out[o] = (jnp.where(has, v, zero), cnt > 0)
        elif s.kind == "avg":
            if s._wide_sum:
                from . import wide_decimal as wd

                cs = wd.normalize_chunks(
                    [accs[f"{o}$c{i}"] for i in range(4)]
                )
                cnt = accs[f"{o}$count"]
                den = jnp.maximum(cnt, 1)
                ot, it = s.output_type, s.input_type
                # exact: 128-bit sum rescaled to the output scale, then
                # one round-half-away 128/64 divide (Int128Math.divide)
                num = wd.rescale(wd.chunks_to_wide(cs), ot.scale - it.scale)
                q = wd.div_round(num, den)
                if wd.is_wide_type(ot):
                    out[o] = (q, cnt > 0)
                else:
                    # narrow output: averages are bounded by the input
                    # magnitude, which fits one limb
                    out[o] = (wd.narrow(q), cnt > 0)
                continue
            ssum = accs[f"{o}$sum"]
            cnt = accs[f"{o}$count"]
            den = jnp.maximum(cnt, 1)
            ot = s.output_type
            if ssum.dtype.kind == "f":
                v = ssum / den
            elif ot is not None and ot.name in ("double", "real"):
                # Trino: avg(integer-type) -> double
                v = ssum.astype(ot.np_dtype) / den
            elif ot is not None and ot.is_decimal and s.input_type is not None:
                # rescale sum to output scale before integer divide
                shift = 10 ** (ot.scale - s.input_type.scale)
                num = ssum * shift
                sign = jnp.sign(num)
                anum = jnp.abs(num)
                q = anum // den
                rem = anum - q * den
                v = sign * (q + (2 * rem >= den))
            else:
                v = ssum // den
            out[o] = (v, cnt > 0)
        elif s.kind in MOMENT_KINDS:
            sm = accs[f"{o}$sum"]
            sq = accs[f"{o}$sumsq"]
            cnt = accs[f"{o}$count"]
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            m2 = jnp.maximum(sq - sm * sm / n, 0.0)
            pop = s.kind in ("var_pop", "stddev_pop")
            if pop:
                var = m2 / n
                valid = cnt > 0
            else:
                var = m2 / jnp.maximum(n - 1, 1.0)
                valid = cnt > 1
            v = jnp.sqrt(var) if s.kind.startswith("stddev") else var
            out[o] = (v, valid)
        elif s.kind == "geometric_mean":
            sl = accs[f"{o}$sumlog"]
            cnt = accs[f"{o}$count"]
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            out[o] = (jnp.exp(sl / n), cnt > 0)
        elif s.kind in BINARY_MOMENT_KINDS:
            sy = accs[f"{o}$sy"]
            sx = accs[f"{o}$sx"]
            sxy = accs[f"{o}$sxy"]
            sxx = accs[f"{o}$sxx"]
            syy = accs[f"{o}$syy"]
            cnt = accs[f"{o}$n"]
            n = jnp.maximum(cnt, 1).astype(jnp.float64)
            cxy = sxy - sx * sy / n
            cxx = jnp.maximum(sxx - sx * sx / n, 0.0)
            cyy = jnp.maximum(syy - sy * sy / n, 0.0)
            if s.kind == "covar_pop":
                v, valid = cxy / n, cnt > 0
            elif s.kind == "covar_samp":
                v, valid = cxy / jnp.maximum(n - 1, 1.0), cnt > 1
            elif s.kind == "corr":
                den = jnp.sqrt(cxx * cyy)
                v = jnp.where(den > 0, cxy / jnp.maximum(den, 1e-300), 0.0)
                valid = (cnt > 0) & (den > 0)
            elif s.kind == "regr_slope":
                v = jnp.where(cxx > 0, cxy / jnp.maximum(cxx, 1e-300), 0.0)
                valid = (cnt > 0) & (cxx > 0)
            else:  # regr_intercept
                slope = jnp.where(cxx > 0, cxy / jnp.maximum(cxx, 1e-300), 0.0)
                v = (sy - slope * sx) / n
                valid = (cnt > 0) & (cxx > 0)
            out[o] = (v, valid)
        elif s.kind in ("bool_and", "bool_or"):
            v = accs[f"{o}$val"]
            cnt = accs[f"{o}$valid"]
            out[o] = (v.astype(bool), cnt > 0)
        elif s.kind in BITWISE_KINDS or s.kind == "checksum":
            v = accs[f"{o}$val"]
            cnt = accs[f"{o}$valid"]
            out[o] = (v, cnt > 0)
        elif s.kind in ("arbitrary", "approx_percentile"):
            v = accs[f"{o}$val"]
            cnt = accs[f"{o}$valid"]
            out[o] = (v, cnt > 0)
        elif s.kind in ("min_by", "max_by"):
            v = accs[f"{o}$val"]
            xvalid = accs[f"{o}$valid"]
            out[o] = (v, xvalid > 0)
        else:
            raise NotImplementedError(s.kind)
    return out


def group_keys_output(
    key_lanes: Sequence[Lane],
    gid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
    starts: Optional[jnp.ndarray] = None,
) -> List[Lane]:
    """Representative key values per group id (first selected row wins).
    With `starts` (sorted-gid run starts from SortedSegments), the
    representative is simply the run-head row — no segment pass."""
    n = gid.shape[0]
    if starts is not None:
        present = starts < n
        safe = jnp.clip(starts, 0, n - 1)
        out = []
        for v, ok in key_lanes:
            out.append((v[safe], ok[safe] & present & sel[safe]))
        return out
    first = _seg_min(
        jnp.where(sel, jnp.arange(n, dtype=jnp.int64), n), gid, capacity
    )
    present = first < n
    safe = jnp.clip(first, 0, n - 1)
    out = []
    for v, ok in key_lanes:
        out.append((v[safe], ok[safe] & present))
    return out
