"""Hash-join equivalent: sorted-build lookup join.

Reference parity: operator/join/ — HashBuilderOperator.java:57 builds a
PagesIndex + generated PagesHashStrategy hash table (JoinCompiler.java:104);
LookupJoinOperator.java:36 probes it per row.

TPU-first redesign: random-access hash tables don't vectorize on TPU, so the
build side becomes a *sorted key array + row permutation* (the bucketed-
sorted table of SURVEY §7), and the probe is a vectorized binary search
(jnp.searchsorted lowers to XLA's O(log n) per-lane search) followed by a
gather of build-side payload rows.  The reference's 64-bit synthetic row
address (SyntheticAddress.java:22) maps to the permutation index.

Round-1 scope: unique build keys (FK/dimension joins — every TPC-H join
except self-joins on lineitem).  Duplicate keys are detected at build time
and surfaced via `dup_count` so the planner can fall back / fail loudly;
the many-to-many expansion (two-pass counting) is the next increment.

Join types: inner, left (probe-outer), semi, anti — all mask-based with
static shapes.  Right/full-outer need the unmatched-build pass
(LookupOuterOperator analog) — future work.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..expr.lower import Lane

I64_MAX = jnp.int64(2**62)


class LookupSource(NamedTuple):
    """The lent lookup source (PartitionedLookupSourceFactory analog)."""

    sorted_keys: jnp.ndarray  # [n] int64, invalid rows pushed to +inf region
    perm: jnp.ndarray  # [n] original row index per sorted slot
    nvalid: jnp.ndarray  # scalar: number of valid build rows
    dup_count: jnp.ndarray  # scalar: number of duplicate keys (0 required)


def build_unique(key: Lane, sel: jnp.ndarray) -> LookupSource:
    """Sort build rows by key; unselected/null rows sort to the end."""
    v, ok = key
    n = v.shape[0]
    live = sel & ok
    kv = jnp.where(live, v.astype(jnp.int64), I64_MAX)
    sorted_keys, perm = jax.lax.sort(
        (kv, jnp.arange(n, dtype=jnp.int64)), num_keys=1
    )
    nvalid = live.sum()
    dup = jnp.sum(
        (sorted_keys[1:] == sorted_keys[:-1]) & (sorted_keys[1:] < I64_MAX)
    )
    return LookupSource(sorted_keys, perm, nvalid, dup)


def probe(
    source: LookupSource, key: Lane, sel: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized lookup: returns (build_row_index, matched mask)."""
    v, ok = key
    pk = v.astype(jnp.int64)
    idx = jnp.searchsorted(source.sorted_keys, pk)
    safe = jnp.clip(idx, 0, source.sorted_keys.shape[0] - 1)
    hit = (source.sorted_keys[safe] == pk) & (pk < I64_MAX)
    matched = sel & ok & hit
    build_row = source.perm[safe]
    return build_row, matched


def gather_build(
    build_cols: Dict[str, Lane], build_row: jnp.ndarray, matched: jnp.ndarray
) -> Dict[str, Lane]:
    """Materialize build-side payload lanes for each probe row."""
    out = {}
    for name, (v, ok) in build_cols.items():
        out[name] = (v[build_row], ok[build_row] & matched)
    return out


class MultiLookupSource(NamedTuple):
    """Build side with duplicate keys allowed (the general PagesHash)."""

    sorted_keys: jnp.ndarray
    perm: jnp.ndarray
    nvalid: jnp.ndarray


def build_multi(key: Lane, sel: jnp.ndarray) -> MultiLookupSource:
    v, ok = key
    n = v.shape[0]
    live = sel & ok
    kv = jnp.where(live, v.astype(jnp.int64), I64_MAX)
    sorted_keys, perm = jax.lax.sort(
        (kv, jnp.arange(n, dtype=jnp.int64)), num_keys=1
    )
    return MultiLookupSource(sorted_keys, perm, live.sum())


def probe_counts(
    source: MultiLookupSource, key: Lane, sel: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-probe-row match count and first-match slot ([lo,hi) range)."""
    v, ok = key
    pk = jnp.where(sel & ok, v.astype(jnp.int64), I64_MAX - 1)
    lo = jnp.searchsorted(source.sorted_keys, pk, side="left")
    hi = jnp.searchsorted(source.sorted_keys, pk, side="right")
    return (hi - lo).astype(jnp.int64), lo


def expand_join(
    source: MultiLookupSource,
    counts: jnp.ndarray,
    lo: jnp.ndarray,
    capacity: int,
    outer: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Expand probe rows by their match multiplicity into a static-capacity
    output (the LookupJoinOperator page-building loop, vectorized).

    Returns (probe_row, build_row, matched, total):
      probe_row[j] : index of the probe row producing output j
      build_row[j] : build-side row index (garbage where not matched)
      matched[j]   : output j is a real (joined) row; for outer=True,
                     unmatched probe rows emit one row with matched=False
      total        : true output size (host checks vs capacity and retries)
    """
    eff = jnp.maximum(counts, 1) if outer else counts
    offsets = jnp.cumsum(eff)
    total = offsets[-1]
    j = jnp.arange(capacity, dtype=jnp.int64)
    probe_row = jnp.searchsorted(offsets, j, side="right")
    probe_row = jnp.clip(probe_row, 0, counts.shape[0] - 1)
    start = offsets[probe_row] - eff[probe_row]
    k = j - start
    slot = jnp.clip(lo[probe_row] + k, 0, source.sorted_keys.shape[0] - 1)
    build_row = source.perm[slot]
    within = j < total
    matched = within & (k < counts[probe_row])
    return probe_row, build_row, matched, total


def composite_key(key_lanes, sel) -> Lane:
    """Combine a multi-column equi-join key into one int64 lane.

    Uses a collision-free pack when domains are known small, else a 64-bit
    mix (splitmix-style) — collision probability ~n^2/2^64; exactness for
    multi-key joins comes with the sort-merge join (future work).
    """
    if len(key_lanes) == 1:
        return key_lanes[0]
    h = jnp.zeros_like(key_lanes[0][0], dtype=jnp.uint64)
    allok = None
    for v, ok in key_lanes:
        x = v.astype(jnp.uint64)
        h = h * jnp.uint64(0x9E3779B97F4A7C15) + x + jnp.uint64(0x632BE59BD9B4E019)
        h = h ^ (h >> jnp.uint64(31))
        allok = ok if allok is None else (allok & ok)
    # keep below the invalid sentinel region of build_unique
    h = (h % jnp.uint64(2**62)).astype(jnp.int64)
    return (h, allok)
