"""Hash-join equivalent: sorted-build lookup join.

Reference parity: operator/join/ — HashBuilderOperator.java:57 builds a
PagesIndex + generated PagesHashStrategy hash table (JoinCompiler.java:104);
LookupJoinOperator.java:36 probes it per row.

TPU-first redesign: random-access hash tables don't vectorize on TPU, so the
build side becomes a *sorted key array + row permutation* (the bucketed-
sorted table of SURVEY §7), and the probe is a SORT-MERGE rank: build and
probe keys are sorted together once and each probe key's position among
the build keys falls out of a cumulative count (XLA's per-lane
binary-search loop — what jnp.searchsorted lowers to — measured ~17x
slower than one extra sort on TPU at millions of rows).  The reference's
64-bit synthetic row address (SyntheticAddress.java:22) maps to the
permutation index.

Exactness: multi-column keys are packed into a 64-bit mix only to *locate*
candidate build rows; every candidate is then verified against the real key
columns (`verify_rows`), the analog of the generated PagesHashStrategy
positionEqualsRow (JoinCompiler.java:104) running after the hash-bucket
probe.  A hash collision therefore costs an extra candidate, never a wrong
row.  Duplicate build keys (or colliding ones) route to the expansion
kernel (`expand_join_slots`), the vectorized LookupJoinOperator
page-building loop with two-pass counting.

Join types: inner, left (probe-outer), semi, anti — all mask-based with
static shapes.  Right/full-outer are planned to left + union of the
null-extended anti side at analysis time (sql/analyzer.py _build_join).
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..expr.lower import Lane

# dead (unselected/NULL-key) build rows sort to the very end: their key is
# pinned to int64 max AND a live-before-dead flag breaks the tie, so the
# first `nvalid` sorted slots are exactly the live rows even when a real
# key equals int64 max — no value is stolen from the key domain
_SENTINEL = 2**63 - 1  # python int (see ops/int128.py const-arg note)


def _sort_live_first(kv, live, n):
    dead = (~live).astype(jnp.int32)
    sorted_keys, _, perm = jax.lax.sort(
        (kv, dead, jnp.arange(n, dtype=jnp.int64)), num_keys=2
    )
    return sorted_keys, perm


def merge_rank(sorted_build: jnp.ndarray, probe: jnp.ndarray, side: str):
    """For each probe key: the number of build keys strictly below it
    (side='left') or at-or-below it (side='right') — searchsorted by
    sort-merge.  One stable single-key sort of [build ++ probe] where the
    concatenation order breaks ties (build-first = right, probe-first =
    left), then a cumulative count of build elements."""
    nb = sorted_build.shape[0]
    m = probe.shape[0]
    if side == "left":
        keys = jnp.concatenate([probe, sorted_build])
        _, perm = jax.lax.sort(
            (keys, jnp.arange(nb + m, dtype=jnp.int64)), num_keys=1
        )
        is_build = perm >= m
        probe_idx = jnp.where(is_build, m, perm)
    else:
        keys = jnp.concatenate([sorted_build, probe])
        _, perm = jax.lax.sort(
            (keys, jnp.arange(nb + m, dtype=jnp.int64)), num_keys=1
        )
        is_build = perm < nb
        probe_idx = jnp.where(is_build, m, perm - nb)
    cb = jnp.cumsum(is_build.astype(jnp.int64))
    # route each cb back to its probe row by SORTING on probe_idx
    # (probes get 0..m-1, build rows sink at m): a scatter here cost
    # ~0.6s at 10M (XLA:TPU ~16M updates/s) vs ~0.15s for the sort
    _, back = jax.lax.sort((probe_idx, cb), num_keys=1)
    return back[:m]


class LookupSource(NamedTuple):
    """The lent lookup source (PartitionedLookupSourceFactory analog)."""

    sorted_keys: jnp.ndarray  # [n] int64, dead rows pushed to the end
    perm: jnp.ndarray  # [n] original row index per sorted slot
    nvalid: jnp.ndarray  # scalar: number of valid build rows
    dup_count: jnp.ndarray  # scalar: number of duplicate keys (0 required)


def build_unique(key: Lane, sel: jnp.ndarray) -> LookupSource:
    """Sort build rows by key; unselected/null rows sort to the end."""
    v, ok = key
    n = v.shape[0]
    live = sel & ok
    kv = jnp.where(live, v.astype(jnp.int64), _SENTINEL)
    sorted_keys, perm = _sort_live_first(kv, live, n)
    nvalid = live.sum()
    dup = jnp.sum(
        (sorted_keys[1:] == sorted_keys[:-1])
        & (jnp.arange(1, n) < nvalid)
    )
    return LookupSource(sorted_keys, perm, nvalid, dup)


def probe(
    source: LookupSource, key: Lane, sel: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized lookup: returns (build_row_index, matched mask)."""
    v, ok = key
    pk = v.astype(jnp.int64)
    idx = merge_rank(source.sorted_keys, pk, side="left")
    safe = jnp.clip(idx, 0, source.sorted_keys.shape[0] - 1)
    hit = (source.sorted_keys[safe] == pk) & (safe < source.nvalid)
    matched = sel & ok & hit
    build_row = source.perm[safe]
    return build_row, matched


def gather_build(
    build_cols: Dict[str, Lane], build_row: jnp.ndarray, matched: jnp.ndarray
) -> Dict[str, Lane]:
    """Materialize build-side payload lanes for each probe row (one
    stacked row-gather per dtype — see filter_project.permute_lanes)."""
    from .filter_project import permute_lanes

    return permute_lanes(build_cols, build_row, extra_ok=matched)


class DirectLookupSource(NamedTuple):
    """Dense-domain build table: rowid+1 scattered at (key - lo), 0 =
    empty slot.  Collision-FREE addressing (no hash, no verification);
    usable only when the planner PROVED the build key unique (strict
    stats walker) and bounded its domain — the runtime still counts
    out-of-domain build keys and reroutes the join to the sorted kernels
    when the proof was wrong (stale stats), so results stay exact.

    Reference analog: the array-based lookup source the generated
    JoinCompiler emits for dense integer keys
    (operator/join/ArrayPositionLinks / PagesHash fast path); TPU-first
    shape: one scatter to build, ONE random gather per probe row —
    measured 0.09s vs the sort-merge rank's 0.21s at 4M probes
    (MICRO_probe.json)."""

    table: jnp.ndarray  # [domain] int32: build row + 1, 0 = empty
    lo: int
    violations: jnp.ndarray  # scalar: live build keys outside the domain


def build_direct(key: Lane, sel: jnp.ndarray, lo: int, domain: int
                 ) -> DirectLookupSource:
    v, ok = key
    live = sel & ok
    kv = v.astype(jnp.int64) - lo
    in_dom = (kv >= 0) & (kv < domain)
    viol = jnp.sum(live & ~in_dom).astype(jnp.int64)
    idx = jnp.where(live & in_dom, kv, domain)  # dropped writes
    n = v.shape[0]
    rowid1 = jnp.arange(1, n + 1, dtype=jnp.int32)
    table = (
        jnp.zeros(domain, dtype=jnp.int32)
        .at[idx]
        .max(rowid1, mode="drop")
    )
    # duplicate detector: each live row gathers its slot back — with a
    # truly unique key every row reads its own write; an overwritten row
    # reads a different rowid.  One cheap gather over the BUILD side, so
    # exactness never rests on the planner's stats being right.
    readback = table[jnp.clip(kv, 0, domain - 1)]
    dups = jnp.sum(
        live & in_dom & (readback != rowid1)
    ).astype(jnp.int64)
    return DirectLookupSource(table, lo, viol + dups)


def probe_direct(
    source: DirectLookupSource, key: Lane, sel: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One gather: build row index + matched mask per probe row.
    Out-of-domain probe keys match nothing — exact, because the build
    violation counter guarantees every live build key IS in-domain."""
    v, ok = key
    kv = v.astype(jnp.int64) - source.lo
    domain = source.table.shape[0]
    in_dom = (kv >= 0) & (kv < domain)
    slot = source.table[jnp.clip(kv, 0, domain - 1)]
    matched = sel & ok & in_dom & (slot > 0)
    return (slot - 1).astype(jnp.int64), matched


class MultiLookupSource(NamedTuple):
    """Build side with duplicate keys allowed (the general PagesHash)."""

    sorted_keys: jnp.ndarray
    perm: jnp.ndarray
    nvalid: jnp.ndarray


def build_multi(key: Lane, sel: jnp.ndarray) -> MultiLookupSource:
    v, ok = key
    n = v.shape[0]
    live = sel & ok
    kv = jnp.where(live, v.astype(jnp.int64), _SENTINEL)
    sorted_keys, perm = _sort_live_first(kv, live, n)
    return MultiLookupSource(sorted_keys, perm, live.sum())


def probe_counts(
    source: MultiLookupSource, key: Lane, sel: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-probe-row match count and first-match slot ([lo,hi) range);
    dead build slots (beyond nvalid) and dead probe rows count zero."""
    v, ok = key
    pk = v.astype(jnp.int64)
    lo = merge_rank(source.sorted_keys, pk, side="left")
    # hi = lo + the run length of the matching key.  Run lengths come
    # from two prefix scans over the SORTED build keys — a segment_sum
    # at build-capacity here measured ~0.5s at 8M rows (XLA:TPU scatter
    # ~16M updates/s), while the scan form is bandwidth-bound:
    #   run_start[i] = index of i's run head   (cummax of boundary idx)
    #   run_len[i]   = run_end[i] - run_start[i] + 1 (reverse cummin)
    nb = source.sorted_keys.shape[0]
    boundary = jnp.concatenate(
        [jnp.ones(1, bool),
         source.sorted_keys[1:] != source.sorted_keys[:-1]]
    )
    idx = jnp.arange(nb, dtype=jnp.int64)
    run_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
    nxt = jnp.concatenate([boundary[1:], jnp.ones(1, bool)])
    run_end = jax.lax.cummin(
        jnp.where(nxt, idx, nb - 1), reverse=True
    )
    run_len = run_end - run_start + 1
    safe = jnp.clip(lo, 0, nb - 1)
    eq = source.sorted_keys[safe] == pk
    hi = jnp.where(eq, lo + run_len[safe], lo)
    lo = jnp.minimum(lo, source.nvalid)
    hi = jnp.minimum(hi, source.nvalid)
    counts = jnp.where(sel & ok, hi - lo, 0).astype(jnp.int64)
    return counts, lo


def expand_join_slots(
    source: MultiLookupSource,
    counts: jnp.ndarray,
    lo: jnp.ndarray,
    capacity: int,
    outer: bool = False,
):
    """Expand probe rows by their match multiplicity into a static-capacity
    output (the LookupJoinOperator page-building loop, vectorized).

    Returns (probe_row, build_row, matched, total, k):
      probe_row[j] : index of the probe row producing output j
      build_row[j] : build-side row index (garbage where not matched)
      matched[j]   : output j is a real (candidate) joined row
      total        : true output size (host checks vs capacity and retries)
      k            : slot offset within the probe row's candidate range;
                     k==0 identifies the one row per probe row that carries
                     the null-extended output when an outer probe row has
                     no surviving match
    """
    eff = jnp.maximum(counts, 1) if outer else counts
    offsets = jnp.cumsum(eff)
    total = offsets[-1]
    j = jnp.arange(capacity, dtype=jnp.int64)
    # output slot -> probe row: scatter each row's id at its start offset,
    # then a running max fills the row's whole range (offsets are
    # monotone; rows with eff=0 own no slots and are dropped)
    starts = offsets - eff
    nrows = counts.shape[0]
    seed = (
        jnp.zeros(capacity, dtype=jnp.int64)
        .at[jnp.where(eff > 0, starts, capacity)]
        .max(jnp.arange(nrows, dtype=jnp.int64), mode="drop")
    )
    probe_row = jax.lax.cummax(seed)
    probe_row = jnp.clip(probe_row, 0, counts.shape[0] - 1)
    start = offsets[probe_row] - eff[probe_row]
    k = j - start
    slot = jnp.clip(lo[probe_row] + k, 0, source.sorted_keys.shape[0] - 1)
    build_row = source.perm[slot]
    within = j < total
    matched = within & (k < counts[probe_row])
    return probe_row, build_row, matched, total, k


def needs_verification(key_lanes) -> bool:
    """True when the locator is a lossy hash that candidates must be
    re-checked against: multi-column keys, or any wide (two-limb)
    decimal key (whose 128 bits cannot pass through one locator)."""
    return len(key_lanes) > 1 or any(
        v.ndim == 2 for v, _ in key_lanes
    )


def verify_rows(
    build_keys, probe_keys, build_row: jnp.ndarray,
    probe_row: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Exact key equality of candidate pairs — the PagesHashStrategy
    positionEqualsRow analog (JoinCompiler.java:104).  Compares every real
    key column; NULL keys never match (SQL equi-join semantics)."""
    eq = None
    for (bv, bok), (pv, pok) in zip(build_keys, probe_keys):
        b, bo = bv[build_row], bok[build_row]
        p = pv if probe_row is None else pv[probe_row]
        po = pok if probe_row is None else pok[probe_row]
        if b.ndim == 2 or p.ndim == 2:
            # wide decimal (either side may be a lane-narrow wide value)
            from . import wide_decimal as wd

            veq = wd.compare(wd.promote(b), wd.promote(p), "==")
        else:
            veq = b == p
        e = veq & bo & po
        eq = e if eq is None else (eq & e)
    return eq


def _canonical_bits(v: jnp.ndarray) -> jnp.ndarray:
    """Lane value -> one uint64 of hash material, IDENTICAL for a
    narrow lane and a two-limb lane holding the same value.  Wide
    decimal arithmetic keeps fast-path lanes narrow even when typed
    wide, so a join/bucket hash must not depend on the lane FORM: a
    wide lane whose value fits one limb hashes as that limb; genuinely
    128-bit values (never equal to any narrow-lane value) fold in the
    high limb.  Callers verify candidates on the real columns."""
    if v.ndim == 2:
        from . import wide_decimal as wd

        lo = v[:, 0].astype(jnp.uint64)
        hi = v[:, 1].astype(jnp.uint64)
        folded = lo ^ (hi * jnp.uint64(0x9E3779B97F4A7C15))
        return jnp.where(wd.fits_narrow(v), lo, folded)
    return v.astype(jnp.uint64)


def _mix(h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """One splitmix-style mixing round.  Module-level so adversarial tests
    can patch in a deliberately weak hash and prove the exact-verification
    path (verify_rows) absorbs collisions."""
    h = h * jnp.uint64(0x9E3779B97F4A7C15) + x + jnp.uint64(0x632BE59BD9B4E019)
    return h ^ (h >> jnp.uint64(31))


def composite_key(key_lanes, sel, force_hash: bool = False) -> Lane:
    """Combine a multi-column equi-join key into one int64 *locator* lane.

    Single-column NARROW keys pass through (value == locator,
    collision-free).  Multi-column keys — and wide (two-limb) decimal
    keys, whose 128 bits cannot ride one locator — get a 64-bit mix used
    only to find candidate rows; callers MUST filter candidates with
    `verify_rows` on the real columns whenever `needs_verification` says
    so — a collision then only costs an extra (rejected) candidate.

    `force_hash` lets callers impose the JOINT decision across both join
    sides: lane forms may differ per side (a wide-typed product keeps a
    narrow fast-path lane), and build/probe locators must come from the
    same function either way.
    """
    if not force_hash and not needs_verification(key_lanes):
        return key_lanes[0]
    n = key_lanes[0][0].shape[0]
    h = jnp.zeros(n, dtype=jnp.uint64)
    allok = None
    for v, ok in key_lanes:
        h = _mix(h, _canonical_bits(v))
        allok = ok if allok is None else (allok & ok)
    # fold into the non-negative int64 range (dead rows are handled by the
    # live-first sort, not by a reserved value region)
    h = (h % jnp.uint64(2**62)).astype(jnp.int64)
    return (h, allok)
