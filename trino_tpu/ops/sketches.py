"""Mergeable approximate-aggregation sketches.

Reference parity: operator/aggregation/ HyperLogLog state
(ApproximateCountDistinctAggregations over airlift HLL) and the qdigest /
tdigest percentile families — the states that make approx_distinct /
approx_percentile DECOMPOSABLE so they split PARTIAL/FINAL across
exchanges instead of gathering raw rows.

TPU-first redesign:
  - HLL: m=512 8-bit registers per group, packed 8-per-int64 into 64
    accumulator lanes.  Register updates are ONE flat segment_max over
    [cap*m] slots (no per-register passes); rank (leading-zero count)
    is computed arithmetically — no clz/bitcast primitives on TPU.
  - percentile: a k-minimum-hash UNIFORM ROW SAMPLE (k=256) per group —
    keep the k rows with smallest per-row hash; merging unions candidate
    sets and re-keeps the k smallest, which is exactly a uniform sample
    of the union.  Quantiles come from the sample (rank error
    ~1/sqrt(k) ≈ 6%), with exact min/max carried alongside so p=0 / p=1
    stay exact and estimates clamp into range.

Both sketches bound device memory by cap * (m or k) transient slots; a
2^30-slot guard (~2M HLL groups / ~4M sample groups) fails loudly rather
than estimate from truncated state.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

HLL_M = 512               # registers: std error 1.04/sqrt(512) ~= 4.6%
HLL_REG_PER_LANE = 8      # 8-bit registers packed into int64 lanes
HLL_LANES = HLL_M // HLL_REG_PER_LANE
_HLL_ALPHA = 0.7213 / (1 + 1.079 / HLL_M)

KMV_K = 256               # sample size: quantile rank error ~1/sqrt(256)

_GOLD = 0x9E3779B97F4A7C15  # python int (see ops/int128.py const-arg note)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x.astype(jnp.uint64) + jnp.uint64(_GOLD))
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _bitlength(w: jnp.ndarray) -> jnp.ndarray:
    """Exact bit length of uint64 values < 2^56, arithmetically: a float
    log2 estimate corrected by exact shifts (f64 rounds above 2^53)."""
    wf = jnp.maximum(w.astype(jnp.float64), 1.0)
    b = jnp.floor(jnp.log2(wf)).astype(jnp.int32)
    b = jnp.clip(b, 0, 56)
    bu = b.astype(jnp.uint64)
    b = b + ((w >> (bu + jnp.uint64(1))) > 0)
    b = b - jnp.where((w >> b.astype(jnp.uint64)) == 0, 1, 0)
    return jnp.where(w == 0, 0, b + 1)


def _guard_cap(cap: int, width: int):
    # 2^30 int32 slots = 4 GB transient state: above the executor's
    # capacity ladder ceiling for realistic group counts (~2M groups),
    # below HBM.  Clear loud failure beyond it — silent estimates from
    # truncated state would be worse than an error.
    if cap * width > (1 << 30):
        raise ValueError(
            f"approximate-aggregation sketch state ({cap} groups x "
            f"{width} slots) exceeds the 2^30-slot device guard; "
            "reduce the group count or use exact count(distinct)"
        )


# --- HyperLogLog ------------------------------------------------------


def hll_accumulate(
    bits: jnp.ndarray, live: jnp.ndarray, gid: jnp.ndarray, cap: int
) -> Dict[int, jnp.ndarray]:
    """Per-group packed HLL registers from value bit material.

    Returns {lane_index: [cap] int64 packed registers}."""
    _guard_cap(cap, HLL_M)
    h = _mix64(bits)
    reg = (h & jnp.uint64(HLL_M - 1)).astype(jnp.int64)
    w = h >> jnp.uint64(9)  # 55-bit window
    rank = jnp.where(live, (56 - _bitlength(w)).astype(jnp.int32), 0)
    seg = jnp.where(live, gid * HLL_M + reg, 0)
    flat = jax.ops.segment_max(
        jnp.where(live, rank, -1), seg, num_segments=cap * HLL_M
    )
    flat = jnp.maximum(flat, 0)
    return _pack(flat.reshape(cap, HLL_M))


def _pack(regs: jnp.ndarray) -> Dict[int, jnp.ndarray]:
    cap = regs.shape[0]
    r = regs.astype(jnp.uint64).reshape(cap, HLL_LANES, HLL_REG_PER_LANE)
    packed = jnp.zeros((cap, HLL_LANES), dtype=jnp.uint64)
    for j in range(HLL_REG_PER_LANE):
        packed = packed | (r[:, :, j] << jnp.uint64(8 * j))
    packed = packed.astype(jnp.int64)
    return {i: packed[:, i] for i in range(HLL_LANES)}


def _unpack(lanes, n: int) -> jnp.ndarray:
    """[n, HLL_M] int32 registers from the packed int64 lanes."""
    cols = []
    for i in range(HLL_LANES):
        word = lanes[i].astype(jnp.uint64)
        for j in range(HLL_REG_PER_LANE):
            cols.append(
                ((word >> jnp.uint64(8 * j)) & jnp.uint64(0xFF)).astype(
                    jnp.int32
                )
            )
    return jnp.stack(cols, axis=1)  # order: lane-major = register index


def hll_pmax_merge(lanes, cap: int, axis) -> Dict[int, jnp.ndarray]:
    """Cross-device HLL union as a register-wise max collective.

    The packed int64 lanes are NOT pmax-mergeable as words — a max of
    two packed words compares the 8-register concatenation
    lexicographically, not each register (the HLL union is the
    ELEMENTWISE register max, Flajolet et al.).  Unpack to [cap, 512]
    int32 registers, pmax over the mesh axis, repack.  Must run inside
    a shard_map program over `axis`."""
    _guard_cap(cap, HLL_M)
    regs = _unpack(lanes, cap)
    regs = jax.lax.pmax(regs, axis)
    return _pack(regs)


def hll_merge(
    lanes, sel: jnp.ndarray, gid: jnp.ndarray, cap: int
) -> Dict[int, jnp.ndarray]:
    """Merge partial packed-register rows into final groups
    (register-wise max)."""
    _guard_cap(cap, HLL_M)
    n = sel.shape[0]
    regs = _unpack(lanes, n)  # [n, HLL_M]
    regs = jnp.where(sel[:, None], regs, 0)
    seg = (gid[:, None] * HLL_M + jnp.arange(HLL_M)[None, :]).reshape(-1)
    flat = jax.ops.segment_max(
        regs.reshape(-1), jnp.where(jnp.repeat(sel, HLL_M), seg, 0),
        num_segments=cap * HLL_M,
    )
    flat = jnp.maximum(flat, 0)
    return _pack(flat.reshape(cap, HLL_M))


def hll_cardinality(lanes, cap: int) -> jnp.ndarray:
    """HLL estimator with linear-counting small-range correction."""
    regs = _unpack(lanes, cap).astype(jnp.float64)  # [cap, m]
    inv = jnp.sum(jnp.exp2(-regs), axis=1)
    raw = _HLL_ALPHA * HLL_M * HLL_M / inv
    zeros = jnp.sum(regs == 0, axis=1)
    small = (raw <= 2.5 * HLL_M) & (zeros > 0)
    linear = HLL_M * jnp.log(HLL_M / jnp.maximum(zeros, 1e-9))
    est = jnp.where(small, linear, raw)
    return jnp.round(est).astype(jnp.int64)


# --- k-minimum-hash uniform sample (percentile sketch) ----------------

_H_EMPTY = 2**62  # python int (see ops/int128.py const-arg note)


def kmv_accumulate(
    v: jnp.ndarray,
    live: jnp.ndarray,
    gid: jnp.ndarray,
    cap: int,
    salt: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-group uniform sample: the k rows with smallest per-ROW hash
    (duplicated values sampled proportionally — the hash is over the row
    index, not the value).  Returns (values [cap*k], hashes [cap*k]);
    empty slots carry hash sentinel _H_EMPTY."""
    _guard_cap(cap, KMV_K)
    n = v.shape[0]
    # fold the VALUE BITS into the per-row hash: a pure row-index hash is
    # identical on every shard, so merged samples would be position-
    # correlated across workers (effective sample k/W when scan order
    # correlates with the value); value bits decorrelate shards while the
    # row index keeps duplicate values individually sampleable
    from .aggregation import _key_bits

    h = (
        _mix64(
            jnp.arange(n, dtype=jnp.uint64)
            ^ _mix64(_key_bits(v))
            ^ jnp.uint64(salt * 2 + 1)
        )
        % jnp.uint64(2**40)
    ).astype(jnp.int64)
    return _kmv_keep_smallest(v, h, live, gid, cap)


def _kmv_keep_smallest(v, h, live, gid, cap):
    n = v.shape[0]
    comp = jnp.where(live, gid * jnp.int64(2**40) + h, jnp.int64(2**62))
    _, order = jax.lax.sort(
        (comp, jnp.arange(n, dtype=jnp.int64)), num_keys=1
    )
    gs = jnp.where(live, gid, cap - 1)[order]
    live_s = live[order]
    first = jax.ops.segment_min(
        jnp.where(live_s, jnp.arange(n, dtype=jnp.int64), n),
        jnp.where(live_s, gs, 0),
        num_segments=cap,
    )
    first = jnp.minimum(first, n)
    rank = jnp.arange(n, dtype=jnp.int64) - first[gs]
    dest = jnp.where(
        live_s & (rank < KMV_K), gs * KMV_K + rank, cap * KMV_K
    )
    vals = (
        jnp.zeros(cap * KMV_K, dtype=v.dtype)
        .at[dest]
        .set(v[order], mode="drop")
    )
    hs = (
        jnp.full(cap * KMV_K, _H_EMPTY, dtype=jnp.int64)
        .at[dest]
        .set(h[order], mode="drop")
    )
    return vals, hs


def kmv_merge(
    vals: jnp.ndarray,
    hs: jnp.ndarray,
    sel: jnp.ndarray,
    gid: jnp.ndarray,
    cap: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Union partial sample rows (each row carries k candidate slots) and
    re-keep the k smallest hashes per final group — still an exact
    uniform sample of the union."""
    _guard_cap(cap, KMV_K)
    n = sel.shape[0]
    live = jnp.repeat(sel, KMV_K) & (hs.reshape(-1) != _H_EMPTY)
    gidr = jnp.repeat(gid, KMV_K)
    return _kmv_keep_smallest(
        vals.reshape(-1), hs.reshape(-1), live, gidr, cap
    )


def kmv_quantile(
    vals: jnp.ndarray,
    hs: jnp.ndarray,
    cap: int,
    q: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Nearest-rank quantile of each group's sample.  Returns
    (value [cap], has_rows [cap])."""
    flat_live = hs != _H_EMPTY
    gidf = jnp.arange(cap * KMV_K, dtype=jnp.int64) // KMV_K
    # sort samples by (group, value) — a 2-key sort on the full-width
    # order encoding so the in-group value order is EXACT
    if jnp.issubdtype(vals.dtype, jnp.floating):
        from .aggregation import f64_order_bits

        enc = (
            f64_order_bits(vals) ^ jnp.uint64(1 << 63)
        ).astype(jnp.int64)
    else:
        enc = vals.astype(jnp.int64)
    gkey = jnp.where(flat_live, gidf, jnp.int64(cap))  # dead rows last
    ntot = cap * KMV_K
    sg, _, order = jax.lax.sort(
        (gkey, enc, jnp.arange(ntot, dtype=jnp.int64)), num_keys=2
    )
    vs = vals[order]
    ls = sg < cap
    gs = jnp.where(ls, sg, cap - 1)
    first = jax.ops.segment_min(
        jnp.where(ls, jnp.arange(ntot, dtype=jnp.int64), ntot),
        jnp.where(ls, gs, 0),
        num_segments=cap,
    )
    counts = jax.ops.segment_sum(
        ls.astype(jnp.int64), jnp.where(ls, gs, 0), num_segments=cap
    )
    first = jnp.minimum(first, ntot)
    rank = jnp.arange(ntot, dtype=jnp.int64) - first[gs]
    target = jnp.floor(
        q * (jnp.maximum(counts, 1) - 1) + 0.5
    ).astype(jnp.int64)
    pick = ls & (rank == target[gs])
    if jnp.issubdtype(vals.dtype, jnp.floating):
        out = jax.ops.segment_max(
            jnp.where(pick, vs, -jnp.inf), jnp.where(ls, gs, 0),
            num_segments=cap,
        )
        out = jnp.where(counts > 0, out, 0.0)
    else:
        out = jax.ops.segment_max(
            jnp.where(pick, vs, jnp.int64(-(2**62))),
            jnp.where(ls, gs, 0), num_segments=cap,
        )
        out = jnp.where(counts > 0, out, 0)
    return out, counts > 0
