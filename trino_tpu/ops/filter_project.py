"""Fused filter + project over column lanes with a selection mask.

Reference parity: operator/project/PageProcessor.java:51 driven by
ScanFilterAndProjectOperator / FilterAndProjectOperator
(LocalExecutionPlanner.visitScanFilterAndProject:1930).

The reference filters into SelectedPositions and runs codegen'd projections
per batch; here the filter produces a boolean selection mask that stays with
the batch (no compaction — XLA fuses mask application into consumers), and
projections are jax-lowered expressions.  Adaptive batch sizing
(PageProcessor MAX_BATCH_SIZE=8192) is unnecessary: tiles are fixed-shape
and XLA handles scheduling.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..expr import ir
from ..expr.lower import Lane, LoweringContext, compile_expr

Batch = Tuple[Dict[str, Lane], jnp.ndarray]  # (columns, selection mask)


def compile_filter_project(
    filter_expr: Optional[ir.Expr],
    projections: List[Tuple[str, ir.Expr]],
    ctx: Optional[LoweringContext] = None,
) -> Callable[[Dict[str, Lane], jnp.ndarray], Batch]:
    """Compile to a pure fn: (cols, sel) -> (out_cols, sel')."""
    fil = compile_expr(filter_expr, ctx) if filter_expr is not None else None
    projs = [(name, compile_expr(e, ctx)) for name, e in projections]

    def apply(cols: Dict[str, Lane], sel: jnp.ndarray) -> Batch:
        if fil is not None:
            v, ok = fil(cols)
            sel = sel & v & ok
        out = {name: p(cols) for name, p in projs}
        return out, sel

    return apply
