"""Fused filter + project over column lanes with a selection mask.

Reference parity: operator/project/PageProcessor.java:51 driven by
ScanFilterAndProjectOperator / FilterAndProjectOperator
(LocalExecutionPlanner.visitScanFilterAndProject:1930).

The reference filters into SelectedPositions and runs codegen'd projections
per batch; here the filter produces a boolean selection mask that stays with
the batch (no compaction — XLA fuses mask application into consumers), and
projections are jax-lowered expressions.  Adaptive batch sizing
(PageProcessor MAX_BATCH_SIZE=8192) is unnecessary: tiles are fixed-shape
and XLA handles scheduling.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..expr import ir
from ..expr.lower import Lane, LoweringContext, compile_expr

Batch = Tuple[Dict[str, Lane], jnp.ndarray]  # (columns, selection mask)


def compile_filter_project(
    filter_expr: Optional[ir.Expr],
    projections: List[Tuple[str, ir.Expr]],
    ctx: Optional[LoweringContext] = None,
) -> Callable[[Dict[str, Lane], jnp.ndarray], Batch]:
    """Compile to a pure fn: (cols, sel) -> (out_cols, sel')."""
    fil = compile_expr(filter_expr, ctx) if filter_expr is not None else None
    projs = [(name, compile_expr(e, ctx)) for name, e in projections]

    def apply(cols: Dict[str, Lane], sel: jnp.ndarray) -> Batch:
        if fil is not None:
            v, ok = fil(cols)
            sel = sel & v & ok
        out = {name: p(cols) for name, p in projs}
        return out, sel

    return apply


def permute_lanes(
    lanes: Dict[str, Lane], idx: jnp.ndarray, extra_ok=None
) -> Dict[str, Lane]:
    """Gather every lane at `idx` via per-dtype STACKED matrix gathers.

    XLA:TPU random gather is per-element-overhead bound (~36M elem/s
    measured); one (n, k) row gather over k stacked columns runs ~2.4x
    faster than k column gathers (MICRO gmicro: 4x i64 0.68s separate
    vs 0.32s stacked at 8.4M).  Lanes are grouped by dtype, stacked,
    row-gathered once, and unstacked; wide (two-limb) lanes contribute
    their limbs as two stack columns.  `extra_ok` optionally ANDs a
    mask into every validity lane (join `matched`)."""
    groups: Dict[object, list] = {}  # dtype -> [(key, array, kind)]
    for s, (v, ok) in lanes.items():
        if v.ndim == 2:  # wide decimal limbs
            groups.setdefault(v.dtype, []).append(((s, "v0"), v[:, 0]))
            groups.setdefault(v.dtype, []).append(((s, "v1"), v[:, 1]))
        else:
            groups.setdefault(v.dtype, []).append(((s, "v"), v))
        groups.setdefault(jnp.dtype(bool), []).append(((s, "ok"), ok))
    got: Dict[object, jnp.ndarray] = {}
    for dt, items in groups.items():
        if len(items) == 1:
            key, arr = items[0]
            got[key] = arr[idx]
            continue
        mat = jnp.stack([a for _, a in items], axis=1)
        taken = mat[idx, :]
        for i, (key, _) in enumerate(items):
            got[key] = taken[:, i]
    out: Dict[str, Lane] = {}
    for s, (v, ok) in lanes.items():
        okg = got[(s, "ok")]
        if extra_ok is not None:
            okg = okg & extra_ok
        if v.ndim == 2:
            out[s] = (
                jnp.stack([got[(s, "v0")], got[(s, "v1")]], axis=-1), okg
            )
        else:
            out[s] = (got[(s, "v")], okg)
    return out
