"""Sort / TopN / Limit kernels.

Reference parity: operator/OrderByOperator.java (+ PagesIndexOrdering
bytecode comparators via OrderingCompiler), operator/TopNOperator.java.

TPU-first: one multi-operand jax.lax.sort call replaces the codegen'd
comparator chain — sort keys are transformed (descending -> negate,
NULLS FIRST/LAST -> sentinel bit as a leading key) and the row permutation
is carried as the last operand; payload columns are gathered afterwards.
TopN is sort + static-length slice (XLA's top-k path applies when keys
reduce to one operand).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..expr.lower import Lane


@dataclasses.dataclass(frozen=True)
class SortKey:
    column: str
    ascending: bool = True
    nulls_first: bool = False  # Trino default: NULLS LAST for ASC


def sort_perm(
    keys: Sequence[SortKey],
    lanes: Dict[str, Lane],
    sel: jnp.ndarray,
) -> jnp.ndarray:
    """Permutation ordering selected rows by keys; unselected rows last."""
    n = sel.shape[0]
    operands: List[jnp.ndarray] = [jnp.logical_not(sel)]
    for k in keys:
        v, ok = lanes[k.column]
        # null ordering as a leading bit per key
        nullbit = jnp.logical_not(ok) if not k.nulls_first else ok
        operands.append(nullbit)
        vv = v.astype(jnp.int8) if v.dtype.kind == "b" else v
        # the nullbit key dominates, so null rows' values need no neutralizing
        operands.append(vv if k.ascending else _negate_for_desc(vv))
    operands.append(jnp.arange(n, dtype=jnp.int64))
    res = jax.lax.sort(tuple(operands), num_keys=len(operands) - 1)
    return res[-1]


def _negate_for_desc(v: jnp.ndarray) -> jnp.ndarray:
    if v.dtype.kind == "f":
        return -v
    if v.dtype.kind == "b":
        return jnp.logical_not(v)
    return -v.astype(jnp.int64)


def apply_perm(
    lanes: Dict[str, Lane], perm: jnp.ndarray, sel: jnp.ndarray
) -> Tuple[Dict[str, Lane], jnp.ndarray]:
    out = {n: (v[perm], ok[perm]) for n, (v, ok) in lanes.items()}
    return out, sel[perm]


def topn(
    keys: Sequence[SortKey],
    lanes: Dict[str, Lane],
    sel: jnp.ndarray,
    n: int,
) -> Tuple[Dict[str, Lane], jnp.ndarray]:
    """Sorted first-n rows (static slice; result capacity = n)."""
    perm = sort_perm(keys, lanes, sel)
    out, s = apply_perm(lanes, perm, sel)
    out = {name: (v[:n], ok[:n]) for name, (v, ok) in out.items()}
    return out, s[:n]


def limit(
    lanes: Dict[str, Lane], sel: jnp.ndarray, n: int, offset: int = 0
) -> Tuple[Dict[str, Lane], jnp.ndarray]:
    """Keep selected rows (offset, offset+n] by running count
    (order-preserving LimitOperator with OFFSET).

    Static-shape: selection mask is trimmed outside the window; array
    capacity is unchanged.
    """
    running = jnp.cumsum(sel.astype(jnp.int64))
    keep = sel & (running <= offset + n)
    if offset:
        keep = keep & (running > offset)
    return lanes, keep
