"""Sort / TopN / Limit kernels.

Reference parity: operator/OrderByOperator.java (+ PagesIndexOrdering
bytecode comparators via OrderingCompiler), operator/TopNOperator.java.

TPU-first: one multi-operand jax.lax.sort call replaces the codegen'd
comparator chain — sort keys are transformed (descending -> negate,
NULLS FIRST/LAST -> sentinel bit as a leading key) and the row permutation
is carried as the last operand; payload columns are gathered afterwards.
TopN is sort + static-length slice (XLA's top-k path applies when keys
reduce to one operand).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..expr.lower import Lane


@dataclasses.dataclass(frozen=True)
class SortKey:
    column: str
    ascending: bool = True
    nulls_first: bool = False  # Trino default: NULLS LAST for ASC


def sort_perm(
    keys: Sequence[SortKey],
    lanes: Dict[str, Lane],
    sel: jnp.ndarray,
) -> jnp.ndarray:
    """Permutation ordering selected rows by keys; unselected rows last."""
    n = sel.shape[0]
    operands: List[jnp.ndarray] = [jnp.logical_not(sel)]
    for k in keys:
        v, ok = lanes[k.column]
        # null ordering as a leading bit per key
        nullbit = jnp.logical_not(ok) if not k.nulls_first else ok
        operands.append(nullbit)
        if v.ndim == 2:
            # wide (two-limb) decimal: two operands, 128-bit signed order
            from . import wide_decimal as wd

            operands.extend(wd.order_operands(v, not k.ascending))
            continue
        vv = v.astype(jnp.int8) if v.dtype.kind == "b" else v
        # the nullbit key dominates, so null rows' values need no neutralizing
        operands.append(vv if k.ascending else _negate_for_desc(vv))
    operands.append(jnp.arange(n, dtype=jnp.int64))
    res = jax.lax.sort(tuple(operands), num_keys=len(operands) - 1)
    return res[-1]


def _negate_for_desc(v: jnp.ndarray) -> jnp.ndarray:
    if v.dtype.kind == "f":
        return -v
    if v.dtype.kind == "b":
        return jnp.logical_not(v)
    # bitwise complement, not negation: -INT64_MIN wraps to itself and
    # would sort first under DESC; ~v is an exact order reversal
    return ~v.astype(jnp.int64)


def apply_perm(
    lanes: Dict[str, Lane], perm: jnp.ndarray, sel: jnp.ndarray
) -> Tuple[Dict[str, Lane], jnp.ndarray]:
    from .filter_project import permute_lanes

    return permute_lanes(lanes, perm), sel[perm]


# python int, not a jnp scalar: module-level jnp constants become
# hidden const args of jitted programs, which the axon tunnel corrupts
# on re-dispatch (see ops/int128.py note)
_SIGN_BITS = 1 << 63  # applied via jnp.uint64(_SIGN_BITS) at trace time


def _order_encode(v, ok, sel, key: SortKey) -> jnp.ndarray:
    """Rank-preserving uint64 for one sort key where LARGER = earlier in
    the output; unselected rows are strictly worst.  The low bit is
    sacrificed for the selection flag, so distinct values may tie — safe,
    because phase 2 re-sorts candidates on the exact keys and the
    completeness check counts encoded ties."""
    if v.ndim == 2:
        # wide decimal: monotone 64-bit approximation; collapsed values
        # surface as counted ties, phase 2 re-sorts on the exact limbs
        from . import wide_decimal as wd

        enc = wd.order_approx64(v).astype(jnp.uint64) ^ jnp.uint64(_SIGN_BITS)
    elif jnp.issubdtype(v.dtype, jnp.floating):
        from .aggregation import f64_order_bits

        # arithmetic IEEE reconstruction — bitcast f64<->u64 is
        # unimplemented in XLA:TPU's x64 rewrite
        enc = f64_order_bits(v)
    elif v.dtype.kind == "b":
        enc = v.astype(jnp.uint64)
    else:
        enc = v.astype(jnp.int64).astype(jnp.uint64) ^ jnp.uint64(_SIGN_BITS)
    if key.ascending:
        enc = ~enc  # top_k picks largest; ascending wants smallest first
    enc = jnp.where(ok, enc, jnp.uint64(0) if not key.nulls_first else ~jnp.uint64(0))
    enc = (enc >> jnp.uint64(1)) | (sel.astype(jnp.uint64) << jnp.uint64(63))
    # top_k wants a signed operand; u64->i64 after flipping the sign bit is
    # the monotone modular wrap (no 64-bit bitcast on TPU)
    return (enc ^ jnp.uint64(_SIGN_BITS)).astype(jnp.int64)


def topn(
    keys: Sequence[SortKey],
    lanes: Dict[str, Lane],
    sel: jnp.ndarray,
    n: int,
    factor: int = 1,
) -> Tuple[Dict[str, Lane], jnp.ndarray, Tuple[jnp.ndarray, int] | None]:
    """Sorted first-n rows (static slice; result capacity = n).

    TPU-first: for small n over large inputs, a full multi-operand
    lexicographic sort compiles slowly on XLA:TPU, so phase 1 runs
    `lax.top_k` on a rank-preserving encoding of the FIRST key only,
    keeping 4n candidates, and phase 2 sorts just those candidates on all
    keys.  Exactness: any row excluded by phase 1 is strictly worse on the
    first key than the n-th candidate, so it cannot reach the top n; ties
    on the encoded key are counted and returned as a (count, capacity)
    check — the executor's retry ladder re-runs with a larger candidate
    set if ties ever exceed it (TopNOperator semantics, never heuristic).
    """
    total = sel.shape[0]
    kprime = max(64, 1 << (max(n, 1) * 4 * factor - 1).bit_length())
    if not keys or kprime >= total:
        perm = sort_perm(keys, lanes, sel)
        out, s = apply_perm(lanes, perm, sel)
        out = {name: (v[:n], ok[:n]) for name, (v, ok) in out.items()}
        return out, s[:n], None
    v, ok = lanes[keys[0].column]
    enc = _order_encode(v, ok, sel, keys[0])
    top_enc, idx = jax.lax.top_k(enc, kprime)
    kth = top_enc[n - 1]
    ties = jnp.sum((enc >= kth) & sel)
    cand = {name: (vv[idx], oo[idx]) for name, (vv, oo) in lanes.items()}
    cand_sel = sel[idx]
    perm = sort_perm(keys, cand, cand_sel)
    out, s = apply_perm(cand, perm, cand_sel)
    out = {name: (v2[:n], ok2[:n]) for name, (v2, ok2) in out.items()}
    return out, s[:n], (ties, kprime)


def limit(
    lanes: Dict[str, Lane], sel: jnp.ndarray, n: int, offset: int = 0
) -> Tuple[Dict[str, Lane], jnp.ndarray]:
    """Keep selected rows (offset, offset+n] by running count
    (order-preserving LimitOperator with OFFSET).

    Static-shape: selection mask is trimmed outside the window; array
    capacity is unchanged.
    """
    running = jnp.cumsum(sel.astype(jnp.int64))
    keep = sel & (running <= offset + n)
    if offset:
        keep = keep & (running > offset)
    return lanes, keep
