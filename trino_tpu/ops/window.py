"""Window function kernels.

Reference parity: operator/WindowOperator.java + operator/window/ (36 files:
PagesWindowIndex, ranking functions RowNumberFunction/RankFunction/
NTileFunction, value functions LagFunction/LeadFunction/FirstValueFunction/
LastValueFunction, FramedWindowFunction/WindowPartition frame logic).

TPU-first redesign: the reference walks each partition row-by-row with a
PagesWindowIndex; here one multi-operand jax.lax.sort groups partitions and
orders peers, then every window function is a closed-form vector program
over the sorted arrays:

  - partition/peer boundaries by adjacent-difference (no hash grouping),
  - partition starts/ends by forward cummax / reverse cummin of boundary
    indices,
  - ranking functions as index arithmetic on those bounds,
  - framed aggregates as exclusive-prefix-sum differences (sum/count/avg)
    or segmented associative scans (running min/max) — O(n log n) total,
    fully static shapes, no per-partition loops.

Frame support matches the common SQL surface: ROWS with UNBOUNDED/
k PRECEDING|FOLLOWING/CURRENT bounds, RANGE with UNBOUNDED/CURRENT bounds
(value-offset RANGE frames are rejected at analysis).  Sliding (bounded)
min/max frames are rejected at analysis — prefix/suffix scans cover the
unbounded-at-one-end cases.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr.lower import Lane

I64_MAX = 2**62  # python int (see ops/int128.py const-arg note)


@dataclasses.dataclass(frozen=True)
class WindowBounds:
    """Per-row partition/peer geometry over the sorted batch."""

    idx: jnp.ndarray         # [n] row index
    gid: jnp.ndarray         # [n] partition id (0-based, unselected rows last)
    part_start: jnp.ndarray  # [n] first row index of this row's partition
    part_end: jnp.ndarray    # [n] last row index of this row's partition
    peer_start: jnp.ndarray  # [n] first row of this row's peer group
    peer_end: jnp.ndarray    # [n] last row of this row's peer group
    peer_boundary: jnp.ndarray  # [n] bool, first row of a peer group
    n: int


def compute_bounds(
    part_lanes: Sequence[Lane],
    order_lanes: Sequence[Lane],
    sel: jnp.ndarray,
) -> WindowBounds:
    """Boundary geometry for rows already sorted by (sel desc, partition
    keys, order keys).  A change in `sel` also opens a partition so the
    unselected tail never merges with a real partition."""
    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    first = jnp.zeros(n, dtype=bool).at[0].set(True)

    def changes(lanes):
        ch = jnp.zeros(n, dtype=bool)
        for v, ok in lanes:
            vv = v.astype(jnp.int8) if v.dtype.kind == "b" else v
            neq = vv[1:] != vv[:-1]
            if neq.ndim == 2:  # wide decimal: a change in either limb
                neq = neq.any(axis=-1)
            ch = ch | jnp.concatenate(
                [jnp.zeros(1, bool), neq | (ok[1:] != ok[:-1])]
            )
        return ch

    sel_change = jnp.concatenate([jnp.zeros(1, bool), sel[1:] != sel[:-1]])
    pb = first | changes(part_lanes) | sel_change
    peer_b = pb | changes(order_lanes)

    gid = jnp.cumsum(pb.astype(jnp.int64)) - 1
    part_start = jax.lax.cummax(jnp.where(pb, idx, 0))
    peer_start = jax.lax.cummax(jnp.where(peer_b, idx, 0))
    # last row of partition p = (next boundary index) - 1, via reverse cummin
    nb = jnp.concatenate([pb[1:], jnp.ones(1, bool)])
    part_end = jax.lax.cummin(jnp.where(nb, idx, n), reverse=True)
    nb_peer = jnp.concatenate([peer_b[1:], jnp.ones(1, bool)])
    peer_end = jax.lax.cummin(jnp.where(nb_peer, idx, n), reverse=True)
    return WindowBounds(
        idx, gid, part_start, part_end, peer_start, peer_end, peer_b, n
    )


# --- frame resolution ---------------------------------------------------


def frame_range(
    frame, b: WindowBounds
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row inclusive [start, end] row-index arrays for a plan
    WindowFrame (unit rows|range; bounds validated by the analyzer)."""
    if frame.unit == "rows":
        start = {
            "unbounded_preceding": b.part_start,
            "preceding": jnp.maximum(b.idx - frame.start_offset, b.part_start),
            "current": b.idx,
            "following": b.idx + frame.start_offset,
        }[frame.start_kind]
        end = {
            "current": b.idx,
            "preceding": b.idx - frame.end_offset,
            "following": jnp.minimum(b.idx + frame.end_offset, b.part_end),
            "unbounded_following": b.part_end,
        }[frame.end_kind]
    else:  # range / groups with unbounded|current bounds only
        start = {
            "unbounded_preceding": b.part_start,
            "current": b.peer_start,
        }[frame.start_kind]
        end = {
            "current": b.peer_end,
            "unbounded_following": b.part_end,
        }[frame.end_kind]
    return start, end


def _prefix_unbounded(frame) -> bool:
    return frame.start_kind == "unbounded_preceding"


def _suffix_unbounded(frame) -> bool:
    return frame.end_kind == "unbounded_following"


# --- ranking ------------------------------------------------------------


def row_number(b: WindowBounds) -> Lane:
    v = b.idx - b.part_start + 1
    return v, jnp.ones(b.n, bool)


def rank(b: WindowBounds) -> Lane:
    v = b.peer_start - b.part_start + 1
    return v, jnp.ones(b.n, bool)


def dense_rank(b: WindowBounds) -> Lane:
    cpeer = jnp.cumsum(b.peer_boundary.astype(jnp.int64))
    safe = jnp.clip(b.part_start, 0, b.n - 1)
    v = cpeer - cpeer[safe] + 1
    return v, jnp.ones(b.n, bool)


def percent_rank(b: WindowBounds, sel: jnp.ndarray) -> Lane:
    size = _partition_size(b, sel)
    r = (b.peer_start - b.part_start).astype(jnp.float64)
    den = jnp.maximum(size - 1, 1).astype(jnp.float64)
    v = jnp.where(size > 1, r / den, 0.0)
    return v, jnp.ones(b.n, bool)


def cume_dist(b: WindowBounds, sel: jnp.ndarray) -> Lane:
    size = _partition_size(b, sel)
    covered = (b.peer_end - b.part_start + 1).astype(jnp.float64)
    v = covered / jnp.maximum(size, 1).astype(jnp.float64)
    return v, jnp.ones(b.n, bool)


def _partition_size(b: WindowBounds, sel: jnp.ndarray) -> jnp.ndarray:
    cnt = jax.ops.segment_sum(
        sel.astype(jnp.int64), b.gid, num_segments=b.n
    )
    return cnt[jnp.clip(b.gid, 0, b.n - 1)]


def ntile(b: WindowBounds, sel: jnp.ndarray, buckets: int) -> Lane:
    size = _partition_size(b, sel)
    rn0 = b.idx - b.part_start
    q, r = size // buckets, size % buckets
    threshold = (q + 1) * r
    big = rn0 // jnp.maximum(q + 1, 1)
    small = r + (rn0 - threshold) // jnp.maximum(q, 1)
    v = jnp.where(rn0 < threshold, big, small) + 1
    return v, jnp.ones(b.n, bool)


# --- value functions ----------------------------------------------------


def shift_value(
    lane: Lane,
    b: WindowBounds,
    offset: int,
    default: Optional[object],
    lead: bool,
) -> Lane:
    """lag/lead: value `offset` rows behind/ahead within the partition,
    else the (constant) default."""
    v, ok = lane
    j = b.idx + offset if lead else b.idx - offset
    in_part = (j <= b.part_end) if lead else (j >= b.part_start)
    safe = jnp.clip(j, 0, b.n - 1)
    vj, okj = v[safe], ok[safe]
    if default is None:
        dv = jnp.zeros((), dtype=v.dtype)
        dok = jnp.zeros((), dtype=bool)
    else:
        dv = jnp.asarray(default, dtype=v.dtype)
        dok = jnp.ones((), dtype=bool)
    take = in_part[..., None] if vj.ndim == 2 else in_part
    return (
        jnp.where(take, vj, dv),
        jnp.where(in_part, okj, dok),
    )


def value_at(lane: Lane, at: jnp.ndarray, nonempty: jnp.ndarray) -> Lane:
    """first_value/last_value: gather the frame-start/end row's value."""
    v, ok = lane
    safe = jnp.clip(at, 0, v.shape[0] - 1)
    return v[safe], ok[safe] & nonempty


def nth_value(
    lane: Lane, start: jnp.ndarray, end: jnp.ndarray, nth: int
) -> Lane:
    v, ok = lane
    at = start + (nth - 1)
    inside = at <= end
    safe = jnp.clip(at, 0, v.shape[0] - 1)
    return v[safe], ok[safe] & inside


# --- framed aggregates --------------------------------------------------


def _excl_cumsum(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate(
        [jnp.zeros(1, dtype=x.dtype), jnp.cumsum(x)]
    )


def framed_sum_count(
    lane: Optional[Lane],
    sel: jnp.ndarray,
    start: jnp.ndarray,
    end: jnp.ndarray,
    count_star: bool = False,
) -> Tuple[Optional[jnp.ndarray], jnp.ndarray]:
    """(sum, count) of lane over the inclusive [start, end] frame.
    lane None (count(*)): counts selected rows."""
    nonempty = end >= start
    s = jnp.clip(start, 0, sel.shape[0] - 1)
    e1 = jnp.clip(end + 1, 0, sel.shape[0])
    if count_star or lane is None:
        ones = sel.astype(jnp.int64)
        c = _excl_cumsum(ones)
        cnt = jnp.where(nonempty, c[e1] - c[s], 0)
        return None, cnt
    v, ok = lane
    live = sel & ok
    if v.dtype.kind == "f":
        masked = jnp.where(live, v, 0.0)
    else:
        masked = jnp.where(live, v.astype(jnp.int64), 0)
    cs = _excl_cumsum(masked)
    cc = _excl_cumsum(live.astype(jnp.int64))
    ssum = jnp.where(nonempty, cs[e1] - cs[s], jnp.zeros((), masked.dtype))
    cnt = jnp.where(nonempty, cc[e1] - cc[s], 0)
    return ssum, cnt


def framed_sum_wide(
    lane: Lane, sel: jnp.ndarray, start: jnp.ndarray, end: jnp.ndarray
):
    """Exact 128-bit framed SUM over (narrow or wide) decimal lanes:
    32-bit chunk exclusive cumsums, frame-end differences, one carry
    normalization — the windowed form of the chunked group SUM
    (DecimalSumAggregation Int128 state)."""
    from . import wide_decimal as wd

    v, ok = lane
    live = sel & ok
    nonempty = end >= start
    s = jnp.clip(start, 0, sel.shape[0] - 1)
    e1 = jnp.clip(end + 1, 0, sel.shape[0])
    chunks = (
        wd.wide_row_chunks(v, live)
        if wd.is_wide(v)
        else wd.narrow_row_chunks(v, live)
    )
    diffs = []
    for c in chunks:
        cs = _excl_cumsum(c)
        diffs.append(jnp.where(nonempty, cs[e1] - cs[s], 0))
    while len(diffs) < 4:
        diffs.append(jnp.zeros_like(diffs[0]))
    wide = wd.chunks_to_wide(wd.normalize_chunks(diffs))
    cc = _excl_cumsum(live.astype(jnp.int64))
    cnt = jnp.where(nonempty, cc[e1] - cc[s], 0)
    return wide, cnt


def _segscan(v: jnp.ndarray, reset: jnp.ndarray, op, reverse: bool):
    """Segmented prefix scan: op-combine values left-to-right (or right-to-
    left), restarting at rows where reset is True (in scan direction).
    Values may carry trailing dims (wide-decimal limb pairs); the reset
    flag broadcasts over them."""

    def combine(a, c):
        f1, v1 = a
        f2, v2 = c
        f2b = f2[..., None] if v2.ndim > f2.ndim else f2
        return (f1 | f2, jnp.where(f2b, v2, op(v1, v2)))

    _, out = jax.lax.associative_scan(combine, (reset, v), reverse=reverse)
    return out


def _range_extreme(
    masked: jnp.ndarray, start: jnp.ndarray, end: jnp.ndarray, op,
    identity,
) -> jnp.ndarray:
    """Per-row range reduction masked[start[i]..end[i]] for ARBITRARY
    per-row ranges (the sliding-frame case the reference covers with
    per-frame re-aggregation, operator/window/).

    TPU-first design: a sparse-table (binary-lifting) reduction instead
    of van Herk's fixed-width two-scan — van Herk needs one block width
    for every row, but RANGE frames and partition-clipped ROWS frames
    give each row its own [start, end].  Level k holds
    T_k[i] = op(masked[i .. i+2^k-1]) built by a static shift+combine;
    any range of width w is two overlapping 2^k blocks where
    k = floor(log2(w)), so each level answers its rows with two gathers.
    O(n log n) combines, static shapes, no sort, empty ranges keep the
    op identity (the caller's count masks them to NULL)."""
    n = masked.shape[0]
    width = jnp.maximum(end - start + 1, 0)
    # floor(log2(width)) per row (width < 1 never queried: out stays id)
    lev = jnp.where(
        width > 0,
        jnp.int64(63) - jnp.int64(jax.lax.clz(
            jnp.maximum(width, 1).astype(jnp.int64))),
        jnp.int64(-1),
    )
    # identity may itself carry trailing dims (a wide-decimal sentinel
    # limb pair); broadcast it to the lane shape either way
    out = jnp.broadcast_to(
        jnp.asarray(identity, dtype=masked.dtype), masked.shape
    )
    tbl = masked
    # levels must include k = floor(log2(n)): a frame spanning the whole
    # batch has width n and queries that top level
    levels = max(1, n.bit_length())
    s_clip = jnp.clip(start, 0, n - 1)
    for k in range(levels):
        hit = lev == k
        if masked.ndim > 1:
            hit = hit[:, None]
        # two overlapping 2^k blocks: [s, s+2^k-1] and [e-2^k+1, e]
        second = jnp.clip(end - (1 << k) + 1, 0, n - 1)
        cand = op(tbl[s_clip], tbl[second])
        out = jnp.where(hit, cand, out)
        # next level: T_{k+1}[i] = op(T_k[i], T_k[i + 2^k]) (tail rows
        # keep their shorter suffix block — never queried past n-1)
        step = 1 << k
        if step < n:
            shifted = jnp.concatenate([tbl[step:], tbl[n - step:]])
            tbl = op(tbl, shifted)
    return out


def framed_minmax(
    lane: Lane,
    sel: jnp.ndarray,
    b: WindowBounds,
    frame,
    kind: str,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(value, count) min/max over frames unbounded at one end (validated
    by the analyzer); prefix/suffix segmented scans, then gather at the
    bounded end."""
    v, ok = lane
    live = sel & ok
    if v.dtype.kind == "f":
        sentinel = jnp.inf if kind == "min" else -jnp.inf
        masked = jnp.where(live, v, sentinel)
    else:
        sentinel = I64_MAX if kind == "min" else -I64_MAX
        masked = jnp.where(live, v.astype(jnp.int64), sentinel)
    op = jnp.minimum if kind == "min" else jnp.maximum
    start, end = frame_range(frame, b)
    _, cnt = framed_sum_count(lane, sel, start, end)
    if _prefix_unbounded(frame):
        pb = jnp.concatenate(
            [jnp.ones(1, bool), b.part_start[1:] != b.part_start[:-1]]
        )
        running = _segscan(masked, pb, op, reverse=False)
        out = running[jnp.clip(end, 0, b.n - 1)]
    elif _suffix_unbounded(frame):
        nb = jnp.concatenate([b.part_start[1:] != b.part_start[:-1],
                              jnp.ones(1, bool)])
        running = _segscan(masked, nb, op, reverse=True)
        out = running[jnp.clip(start, 0, b.n - 1)]
    else:
        # sliding frame (bounded both ends): per-row range reduction
        out = _range_extreme(masked, start, end, op, sentinel)
    return out, cnt


# --- wide (two-limb) decimal min/max ------------------------------------
# decimal(19..38) lanes are (n, 2) int64: limb 0 the low 64 bits
# (unsigned), limb 1 the high 64 bits (signed) — Int128ArrayBlock layout.
# Ordering is limb-wise: compare high limbs signed, tie-break on low
# limbs UNsigned (XOR the sign bit turns unsigned compare into signed).

_WIDE_SIGN = np.int64(-(2**63))


def _wide_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    lo_a = a[..., 0] ^ _WIDE_SIGN
    lo_b = b[..., 0] ^ _WIDE_SIGN
    return (a[..., 1] < b[..., 1]) | (
        (a[..., 1] == b[..., 1]) & (lo_a < lo_b)
    )


def _wide_min_op(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(_wide_less(x, y)[..., None], x, y)


def _wide_max_op(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(_wide_less(y, x)[..., None], x, y)


def wide_sentinel(kind: str) -> np.ndarray:
    """Identity limb pair for wide min/max.  hi = ±(2^63 - 1) strictly
    dominates every decimal(38) value (|hi limb| <= 5.5e18 < 2^63 - 1).
    min/max only compare and select — never add — so the full int64
    range is safe here (unlike I64_MAX's 2^62 headroom for sums)."""
    hi = np.int64(2**63 - 1)
    if kind == "min":
        return np.array([-1, hi], dtype=np.int64)  # lo = all ones
    return np.array([0, -hi], dtype=np.int64)


def framed_minmax_wide(
    lane: Lane,
    sel: jnp.ndarray,
    b: WindowBounds,
    frame,
    kind: str,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(value, count) min/max over wide-decimal (n, 2) lanes: the same
    prefix/suffix segmented scans and sparse-table range reduction as
    framed_minmax, with the scalar compare replaced by the limb-wise
    one and whole limb PAIRS selected per combine."""
    v, ok = lane
    live = sel & ok
    sent = wide_sentinel(kind)
    masked = jnp.where(live[:, None], v, sent)
    op = _wide_min_op if kind == "min" else _wide_max_op
    start, end = frame_range(frame, b)
    # frame count inline (framed_sum_count sums scalar lanes only)
    nonempty = end >= start
    s = jnp.clip(start, 0, b.n - 1)
    e1 = jnp.clip(end + 1, 0, b.n)
    cc = _excl_cumsum(live.astype(jnp.int64))
    cnt = jnp.where(nonempty, cc[e1] - cc[s], 0)
    if _prefix_unbounded(frame):
        pb = jnp.concatenate(
            [jnp.ones(1, bool), b.part_start[1:] != b.part_start[:-1]]
        )
        running = _segscan(masked, pb, op, reverse=False)
        out = running[jnp.clip(end, 0, b.n - 1)]
    elif _suffix_unbounded(frame):
        nb = jnp.concatenate([b.part_start[1:] != b.part_start[:-1],
                              jnp.ones(1, bool)])
        running = _segscan(masked, nb, op, reverse=True)
        out = running[jnp.clip(start, 0, b.n - 1)]
    else:
        out = _range_extreme(masked, start, end, op, sent)
    return out, cnt
