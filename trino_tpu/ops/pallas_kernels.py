"""Pallas TPU kernels for the hot irregular operators.

Reference parity: the runtime-codegen inner loops the reference JIT-compiles
(FlatHashStrategyCompiler / AccumulatorCompiler bytecode) — here hand-tiled
TPU kernels for the cases where XLA's generic lowering leaves performance on
the table.  First citizen: the grouped segment-sum backing low-cardinality
aggregation (TPC-H Q1 shape): XLA lowers scatter-adds near-serially on TPU
(~8M updates/s measured); this kernel streams the input once through VMEM
and accumulates every group in registers, ~5x faster at SF1 shapes.

Axon-tunnel constraint (measured): the remote Mosaic compile helper
accepts GRID-FREE pallas kernels but rejects gridded ones ("tpu_compile
_helper subprocess exit code 1").  The grid is therefore replaced by an
XLA-level `lax.scan` over VMEM-sized row chunks of a no-grid kernel — the
kernel compiles once, the scan streams the chunks, and the per-chunk
[groups, 128] partials are folded by XLA adds (cheap).

Exact int64 sums with no 64-bit in-kernel math: values split into four
16-bit planes (int32-safe), per-chunk per-group plane sums accumulate in
int32 (<= 2048 rows * 65535 < 2^31), cross-chunk accumulation in int64,
and the plane recombination wraps mod 2^64 exactly like int64 addition.

Enabled by default on the TPU backend; TRINO_TPU_PALLAS=0 disables.
CPU tests run the same kernels in pallas interpret mode.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

try:  # pallas import is environment-sensitive; the engine degrades to XLA
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    HAVE_PALLAS = False

LANES = 128
CHUNK_ROWS = 2048       # [2048, 128] int32 tile = 1 MB VMEM per operand
MAX_GROUPS = 32         # scratch is [4 * gpad, 128] int32
N_PLANES = 4            # 16-bit planes per int64


@functools.lru_cache(maxsize=1)
def enabled() -> bool:
    """Pallas hot path active?  On by default on TPU (the scan-wrapped
    no-grid form compiles through the tunnel); off on CPU where XLA's
    segment ops are fine and interpret mode would be slow."""
    if not HAVE_PALLAS or os.environ.get("TRINO_TPU_PALLAS") == "0":
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _plane_kernel(g_ref, c0_ref, c1_ref, c2_ref, c3_ref, o_ref, *, gpad):
    """No-grid kernel: one [CHUNK_ROWS, 128] tile -> per-group sums of the
    four 16-bit planes, [4 * gpad, 128] int32."""
    gids = g_ref[...]
    zero = jnp.zeros((), dtype=jnp.int32)
    outs = []
    for c_ref in (c0_ref, c1_ref, c2_ref, c3_ref):
        vals = c_ref[...]
        for g in range(gpad):  # static unroll; gpad <= MAX_GROUPS
            # dtype pinned to int32: under x64, jnp.sum would promote to
            # int64, whose in-kernel conversion recurses in Mosaic lowering
            outs.append(
                jnp.sum(
                    jnp.where(gids == g, vals, zero), axis=0,
                    dtype=jnp.int32,
                )
            )
    o_ref[...] = jnp.stack(outs)


def grouped_sum_i64(
    values: jnp.ndarray, gid: jnp.ndarray, groups: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact int64 segment-sum into `groups` buckets, one pass."""
    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable")
    assert groups <= MAX_GROUPS, groups
    n = values.shape[0]
    gpad = max(8, ((groups + 7) // 8) * 8)
    per_chunk = CHUNK_ROWS * LANES
    nchunks = max(1, -(-n // per_chunk))
    padded = nchunks * per_chunk
    v = jnp.zeros(padded, dtype=jnp.int64).at[:n].set(
        values.astype(jnp.int64)
    )
    g = jnp.full(padded, -1, dtype=jnp.int32).at[:n].set(
        gid.astype(jnp.int32)
    )
    planes = [
        ((v >> jnp.int64(16 * k)) & jnp.int64(0xFFFF))
        .astype(jnp.int32)
        .reshape(nchunks, CHUNK_ROWS, LANES)
        for k in range(N_PLANES)
    ]
    g3 = g.reshape(nchunks, CHUNK_ROWS, LANES)
    call = pl.pallas_call(
        functools.partial(_plane_kernel, gpad=gpad),
        out_shape=jax.ShapeDtypeStruct((N_PLANES * gpad, LANES), jnp.int32),
        interpret=interpret,
    )

    def body(acc, xs):
        gc, c0, c1, c2, c3 = xs
        return acc + call(gc, c0, c1, c2, c3).astype(jnp.int64), None

    acc0 = jnp.zeros((N_PLANES * gpad, LANES), dtype=jnp.int64)
    acc, _ = jax.lax.scan(body, acc0, (g3, *planes))
    lane_sums = jnp.sum(acc, axis=1)  # [4 * gpad]
    out = jnp.zeros(gpad, dtype=jnp.int64)
    for k in range(N_PLANES):
        out = out + (
            lane_sums[k * gpad : (k + 1) * gpad] << jnp.int64(16 * k)
        )
    return out[:groups]


def _count_kernel(g_ref, m_ref, o_ref, *, gpad):
    """No-grid kernel: per-group counts of a [CHUNK_ROWS, 128] 0/1 f32
    mask tile -> [gpad, 128] f32 (exact: per-lane partials <= 2048 rows,
    far below f32's 2^24 integer range)."""
    gids = g_ref[...]
    mask = m_ref[...]
    zero = jnp.zeros((), dtype=jnp.float32)
    o_ref[...] = jnp.stack(
        [
            jnp.sum(jnp.where(gids == g, mask, zero), axis=0)
            for g in range(gpad)
        ]
    )


def grouped_count(
    flags: jnp.ndarray, gid: jnp.ndarray, groups: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact int64 per-group count of set flags, one streaming pass.

    Measured on the bench TPU at 6M rows x 9 groups: ~0.1s vs ~1.4s for
    XLA's masked/scatter lowering — counts are the single-f32-plane case
    where the VPU reduction wins.  (General int64 sums need 4x int32
    planes, measured SLOWER than XLA [9.9s vs 1.4s]: int element ops lack
    VPU MACs, so wide sums deliberately stay on the XLA path — that
    measured comparison is the recorded fallback decision.)"""
    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable")
    assert groups <= MAX_GROUPS, groups
    n = flags.shape[0]
    gpad = max(8, ((groups + 7) // 8) * 8)
    per_chunk = CHUNK_ROWS * LANES
    nchunks = max(1, -(-n // per_chunk))
    padded = nchunks * per_chunk
    m = jnp.zeros(padded, dtype=jnp.float32).at[:n].set(
        flags.astype(jnp.float32)
    )
    g = jnp.full(padded, -1, dtype=jnp.int32).at[:n].set(
        gid.astype(jnp.int32)
    )
    m3 = m.reshape(nchunks, CHUNK_ROWS, LANES)
    g3 = g.reshape(nchunks, CHUNK_ROWS, LANES)
    call = pl.pallas_call(
        functools.partial(_count_kernel, gpad=gpad),
        out_shape=jax.ShapeDtypeStruct((gpad, LANES), jnp.float32),
        interpret=interpret,
    )

    def body(acc, xs):
        gc, mc = xs
        # cross-chunk accumulation in f64 (exact to 2^53 counts)
        return acc + call(gc, mc).astype(jnp.float64), None

    acc, _ = jax.lax.scan(
        body, jnp.zeros((gpad, LANES), dtype=jnp.float64), (g3, m3)
    )
    return jnp.sum(acc, axis=1).astype(jnp.int64)[:groups]


def _fused_agg_kernel(*refs, names, gpad, rpad, emit):
    """No-grid megakernel: one [CHUNK_ROWS, 128] tile of every
    referenced scan column -> per-(term, group) int32 partial sums,
    [rpad, 128].  `emit` is the plan-time-compiled closure producing
    (predicate tile | None, group-id tile | None, term value tiles);
    all of its arithmetic is interval-proven int32 (ops/megakernel).
    One VMEM pass: each column is read exactly once per chunk and the
    filter, group codes and every aggregate plane come out of it."""
    live = refs[0][...]
    cols = {nm: r[...] for nm, r in zip(names, refs[1:-1])}
    o_ref = refs[-1]
    pred, gid, vals = emit(cols)
    mask = live != 0
    if pred is not None:
        mask = mask & pred
    zero = jnp.zeros((), dtype=jnp.int32)
    outs = []
    for tv in vals:
        tvm = jnp.where(mask, tv, zero)
        if gid is None:  # global aggregate: one group, no compare
            # dtype pinned to int32 (in-kernel int64 conversion
            # recurses in Mosaic lowering, same as _plane_kernel)
            outs.append(jnp.sum(tvm, axis=0, dtype=jnp.int32))
        else:
            for g in range(gpad):  # static unroll; gpad <= MAX_GROUPS
                outs.append(
                    jnp.sum(
                        jnp.where(gid == g, tvm, zero), axis=0,
                        dtype=jnp.int32,
                    )
                )
    zrow = jnp.zeros((LANES,), dtype=jnp.int32)
    while len(outs) < rpad:  # sublane-align the stacked output
        outs.append(zrow)
    o_ref[...] = jnp.stack(outs)


def fused_agg_sums(
    cols: dict, live: jnp.ndarray, emit, n_terms: int, groups: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused scan->filter->aggregate: stream every column once, return
    exact int64 per-(term, group) sums, [n_terms, groups].

    Same streaming scheme as grouped_sum_i64: the grid-free kernel is
    wrapped in an XLA `lax.scan` over [CHUNK_ROWS, 128] chunks (the
    recorded Mosaic tunnel constraint), per-chunk partials accumulate
    in int32 (term bounds proven by ops/megakernel keep them exact),
    cross-chunk accumulation runs in int64."""
    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable")
    assert groups <= MAX_GROUPS, groups
    names = tuple(sorted(cols))
    n = live.shape[0]
    gpad = 1 if groups == 1 else max(8, ((groups + 7) // 8) * 8)
    nrows = n_terms * gpad
    rpad = max(8, ((nrows + 7) // 8) * 8)
    per_chunk = CHUNK_ROWS * LANES
    nchunks = max(1, -(-n // per_chunk))
    padded = nchunks * per_chunk

    def tiles(a):
        return (
            jnp.zeros(padded, dtype=jnp.int32)
            .at[:n].set(a.astype(jnp.int32))
            .reshape(nchunks, CHUNK_ROWS, LANES)
        )

    l3 = tiles(live)
    c3 = [tiles(cols[nm]) for nm in names]
    call = pl.pallas_call(
        functools.partial(
            _fused_agg_kernel, names=names,
            gpad=(None if groups == 1 else gpad), rpad=rpad, emit=emit,
        ),
        out_shape=jax.ShapeDtypeStruct((rpad, LANES), jnp.int32),
        interpret=interpret,
    )

    def body(acc, xs):
        return acc + call(*xs).astype(jnp.int64), None

    acc0 = jnp.zeros((rpad, LANES), dtype=jnp.int64)
    acc, _ = jax.lax.scan(body, acc0, (l3, *c3))
    lane_sums = jnp.sum(acc, axis=1)[:nrows]
    return lane_sums.reshape(n_terms, gpad)[:, :groups]


def seg_count_maybe(flags: jnp.ndarray, gid: jnp.ndarray, cap: int):
    """Pallas-or-None per-group count of 0/1 flags; None = caller falls
    back to the XLA segment sum."""
    if (
        not enabled()
        or cap > MAX_GROUPS
        or flags.ndim != 1
        or flags.shape[0] < 4 * CHUNK_ROWS * LANES
    ):
        return None
    return grouped_count(flags, gid, cap)


# Every pallas kernel body registers here (scripts/check_donation.py
# enforces it): the entry keys must match the `def *_kernel` names and
# the mode strings join the executor's kernel profile.
KERNEL_REGISTRY = {
    "_plane_kernel": {
        "mode": "pallas",
        "wrapper": "grouped_sum_i64",
        "what": "per-group 16-bit plane sums (exact int64 segment sum)",
    },
    "_count_kernel": {
        "mode": "pallas",
        "wrapper": "grouped_count",
        "what": "per-group single-f32-plane mask counts",
    },
    "_fused_agg_kernel": {
        "mode": "megakernel",
        "wrapper": "fused_agg_sums",
        "what": "fused scan->filter->aggregate per-(term, group) sums",
    },
}
