"""Pallas TPU kernels for the hot irregular operators.

Reference parity: the runtime-codegen inner loops the reference JIT-compiles
(FlatHashStrategyCompiler / AccumulatorCompiler bytecode) — here hand-tiled
TPU kernels for the cases where XLA's generic lowering leaves performance on
the table.  First citizen: the grouped segment-sum that backs low-cardinality
hash aggregation (TPC-H Q1 shape): scatter-add lowers poorly on TPU (no
scatter unit), and the one-hot masked reduction streams the input once per
group; this kernel streams the input ONCE, accumulating all groups in a
VMEM scratch tile.

Grid: one program per row-block; each block loads [block, 128]-tiled values
and group ids into VMEM, accumulates into a [groups, 128] scratch via
in-VMEM masked adds (groups is small), and the final program folds the lane
dimension.  Accumulation is float64-free: int64 is kept as values fit
(engine decimals are scaled int64) — pallas TPU supports int32 natively, so
the kernel splits int64 into hi/lo int32 planes and recombines on the host
side of the jit boundary.

Enabled with TRINO_TPU_PALLAS=1 (off by default: the axon tunnel backend's
remote Mosaic compiler currently rejects gridded/int-input pallas kernels
— "failed to legalize func.return" — though trivial f32 kernels compile;
on a directly-attached TPU the kernels lower normally).  Unit tests run in
pallas interpret mode on CPU and check bit-exactness of the int64 path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas import is environment-sensitive; the engine degrades to XLA
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    HAVE_PALLAS = False

LANES = 128
BLOCK_ROWS = 8  # sublane tile for int32/float32 inputs


def _grouped_sum_kernel(gid_ref, val_ref, out_ref, *, gpad: int):
    """One grid step: accumulate this [rows, 128] tile into out[gpad, 128].

    out_ref is an accumulator output revisited by every grid step (the
    rolling-output pattern): zero it on the first step, then add this
    block's per-group masked sums as one full-tile read-modify-write
    (per-row indexed writes fail Mosaic legalization on some backends).
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    vals = val_ref[...]
    gids = gid_ref[...]
    rows = [
        jnp.sum(jnp.where(gids == g, vals, 0).astype(out_ref.dtype), axis=0)
        for g in range(gpad)  # gpad is small and static: unrolled
    ]
    out_ref[...] += jnp.stack(rows)


def grouped_sum_f32(
    values: jnp.ndarray, gid: jnp.ndarray, groups: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Segment-sum float32 values into `groups` buckets with one pass.

    values/gid: 1-D arrays; padded internally to [blocks*8, 128] tiles.
    Returns float64[groups] (lane folding happens in f64 for exactness).
    """
    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable")
    n = values.shape[0]
    per_block = BLOCK_ROWS * LANES
    blocks = max(1, -(-n // per_block))
    padded = blocks * per_block
    # output tile sublanes must be 8-aligned for f32 (Mosaic tiling)
    gpad = max(8, ((groups + 7) // 8) * 8)
    v = jnp.zeros(padded, dtype=jnp.float32).at[:n].set(
        values.astype(jnp.float32)
    )
    g = jnp.full(padded, -1, dtype=jnp.int32).at[:n].set(
        gid.astype(jnp.int32)
    )
    v2 = v.reshape(blocks * BLOCK_ROWS, LANES)
    g2 = g.reshape(blocks * BLOCK_ROWS, LANES)
    out = pl.pallas_call(
        functools.partial(_grouped_sum_kernel, gpad=gpad),
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((gpad, LANES), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((gpad, LANES), jnp.float32),
        interpret=interpret,
    )(g2, v2)
    # fold lanes in f64: per-cell partial sums can exceed f32's exact
    # integer range once multiplied by 128 lanes
    return jnp.sum(out.astype(jnp.float64), axis=1)[:groups]


def grouped_sum_i64(
    values: jnp.ndarray, gid: jnp.ndarray, groups: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact int64 segment-sum via 8-bit planes (pallas TPU has no native
    int64): each plane's per-lane f32 accumulator stays below 2^24
    (255 * rows/128 addends — callers must bound rows at ~4M per call, as
    ops/aggregation._seg_sum does), lanes fold in f64, recombination wraps
    mod 2^64 exactly like int64 addition."""
    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable")
    v = values.astype(jnp.int64)
    out = jnp.zeros(groups, dtype=jnp.int64)
    for shift in range(0, 64, 8):
        plane = ((v >> shift) & jnp.int64(0xFF)).astype(jnp.float32)
        s = grouped_sum_f32(plane, gid, groups, interpret=interpret)
        out = out + (s.astype(jnp.int64) << shift)
    return out
