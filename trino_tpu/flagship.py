"""Flagship compiled kernel: the TPC-H Q1 fragment as a pure jittable step.

This is the engine's "forward pass": scan->filter->project->group-by over
lineitem, built from the production components (expression lowering +
aggregation kernels), exposed as a standalone function over column arrays
for compile checks and microbenchmarks (BenchmarkPageProcessor.java:67
analog — the reference's hand-rolled JMH kernel plays the same role).
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from . import types as T
from .connectors import tpch
from .expr import ir
from .expr.functions import arith_result_type, days_from_civil
from .expr.lower import LoweringContext, compile_expr
from .ops import aggregation as agg_ops
from .ops.aggregation import AggSpec

DEC = T.decimal(12, 2)


def _q1_exprs():
    qty = ir.ColumnRef(DEC, "l_quantity")
    price = ir.ColumnRef(DEC, "l_extendedprice")
    disc = ir.ColumnRef(DEC, "l_discount")
    tax = ir.ColumnRef(DEC, "l_tax")
    ship = ir.ColumnRef(T.DATE, "l_shipdate")
    one = ir.Constant(T.decimal(1, 0), 1)
    sub_t = arith_result_type("subtract", one.type, DEC)
    one_minus = ir.Call(sub_t, "subtract", (one, disc))
    disc_price_t = arith_result_type("multiply", DEC, sub_t)
    disc_price = ir.Call(disc_price_t, "multiply", (price, one_minus))
    add_t = arith_result_type("add", one.type, DEC)
    one_plus_tax = ir.Call(add_t, "add", (one, tax))
    charge_t = arith_result_type("multiply", disc_price_t, add_t)
    charge = ir.Call(charge_t, "multiply", (disc_price, one_plus_tax))
    cutoff = days_from_civil(1998, 12, 1) - 90
    filt = ir.Comparison("<=", ship, ir.Constant(T.DATE, cutoff))
    return filt, disc_price, charge, disc_price_t, charge_t


def build_q1_step():
    """Returns a jittable fn(cols: dict[str, array]) -> outputs tuple."""
    filt_e, disc_price_e, charge_e, dp_t, ch_t = _q1_exprs()
    ctx = LoweringContext({})
    f_filt = compile_expr(filt_e, ctx)
    f_dp = compile_expr(disc_price_e, ctx)
    f_ch = compile_expr(charge_e, ctx)

    specs = [
        AggSpec("sum", "l_quantity", "sum_qty", DEC, T.decimal(18, 2)),
        AggSpec("sum", "l_extendedprice", "sum_base", DEC, T.decimal(18, 2)),
        AggSpec("sum", "disc_price", "sum_disc", dp_t, T.decimal(18, dp_t.scale)),
        AggSpec("sum", "charge", "sum_charge", ch_t, T.decimal(18, ch_t.scale)),
        AggSpec("avg", "l_quantity", "avg_qty", DEC, T.decimal(18, 4)),
        AggSpec("avg", "l_extendedprice", "avg_price", DEC, T.decimal(18, 4)),
        AggSpec("avg", "l_discount", "avg_disc", DEC, T.decimal(18, 4)),
        AggSpec("count_star", None, "count_order"),
    ]

    def step(cols: Dict[str, jnp.ndarray]):
        n = cols["l_quantity"].shape[0]
        ones = jnp.ones(n, dtype=bool)
        lanes = {k: (v, ones) for k, v in cols.items()}
        fv, fok = f_filt(lanes)
        sel = fv & fok
        lanes["disc_price"] = f_dp(lanes)
        lanes["charge"] = f_ch(lanes)
        keys = [lanes["l_returnflag"], lanes["l_linestatus"]]
        gid, cap = agg_ops.direct_group_ids(keys, [3, 2])
        accs = agg_ops.accumulate(specs, lanes, gid, sel, cap)
        out = agg_ops.finalize(specs, accs)
        present = (
            jnp.zeros(cap, dtype=jnp.int64)
            .at[gid].add(sel.astype(jnp.int64))
            > 0
        )
        return {"present": present, **{k: v for k, (v, _) in out.items()}}

    return step


def q1_example_args(sf: float = 0.001) -> Tuple[Dict[str, jnp.ndarray]]:
    cols_needed = [
        "l_quantity", "l_extendedprice", "l_discount", "l_tax",
        "l_shipdate", "l_returnflag", "l_linestatus",
    ]
    values, dicts, count = tpch.generate("lineitem", sf, columns=cols_needed)
    cols = {c: jnp.asarray(values[c]) for c in cols_needed}
    return (cols,)
