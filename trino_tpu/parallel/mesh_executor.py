"""Distributed execution over a jax device mesh.

Reference parity: the distributed dataflow stack —
  - split assignment across workers (SourcePartitionedScheduler /
    NodeScheduler/UniformNodeSelector): table splits sharded over the
    mesh's 'workers' axis
  - exchanges (operator/exchange, execution/buffer + HTTP page shuffle,
    HttpPageBufferClient.java:98): XLA collectives over ICI inside one
    shard_map program —
      partial->final aggregation    = psum / all-gather + re-merge
      broadcast join build side     = all_gather  (BroadcastOutputBuffer /
                                       FIXED_BROADCAST_DISTRIBUTION)
      gathering exchange at root    = all_gather  (SINGLE distribution)
      hash repartition              = all_to_all  (parallel/shuffle.py,
                                       FIXED_HASH_DISTRIBUTION)
  - DistributedQueryRunner's "N servers in one process" test story maps
    to N mesh devices in one process (virtual CPU devices in tests).

The program is SPMD: every device runs the same fragment over its split
shard; collectives implement the exchange boundaries that the reference
places with AddExchanges (optimizations/AddExchanges.java:138).  Batch
.replicated tracks which intermediate results are device-identical
(the SINGLE vs partitioned distribution property of PlanFragments).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P_

from ..catalog import CatalogManager
from ..exec.local import (
    Batch,
    ExecutionError,
    LocalExecutor,
    merge_pages_to_arrays,
    _pad_capacity,
    _shape_summary,
    _TraceCtx,
)
from ..obs import compile_observatory as _compile_obs
from ..utils.tracing import TRACER
from ..expr import ir
from ..expr.lower import compile_expr
from ..ops import aggregation as agg_ops
from ..ops import join as join_ops
from ..ops import sketches
from ..ops import sort as sort_ops
from . import shuffle
from ..page import Column, Page
from ..plan import nodes as P
from ..runtime import Breadcrumb, DeviceFaultError

AXIS = "workers"


def _is_hll_lane(spec, name: str) -> bool:
    """True for the packed-register HLL accumulator lanes of
    approx_distinct — the one sketched state a mesh collective CAN merge
    (register-wise max); other sketched lanes (k-min-hash samples) still
    need the gathered merge path."""
    return spec.kind == "approx_distinct" and "$hll" in name


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map moved out of jax.experimental and renamed its
    replication-check kwarg (check_rep -> check_vma) across jax
    releases; resolve whichever this install provides."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except TypeError:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def default_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    return Mesh(np.array(devs[:n]), (AXIS,))


def _agather(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.all_gather(x, AXIS, axis=0, tiled=True)


def _shuffle_chunk(cap: int, ndev: int, factor: int, quantize=None) -> int:
    """Per-destination chunk capacity for a hash repartition: expected
    cap/ndev rows per bucket with 2x skew slack, grown by the retry-ladder
    factor on overflow.  `quantize` is the executor ladder's rung
    function (plain lane alignment when absent)."""
    q = quantize or _pad_capacity
    return q(max(128, (2 * cap * factor) // ndev))


def _decode_direct_keys(domains, cap):
    """Recover group key codes from the dense mixed-radix group id —
    avoids cross-device gathers of representative rows."""
    gids = jnp.arange(cap, dtype=jnp.int64)
    radixes = [d + 1 for d in domains]
    strides = []
    s = 1
    for r in reversed(radixes):
        strides.append(s)
        s *= r
    strides = list(reversed(strides))
    out = []
    for dom, stride, radix in zip(domains, strides, radixes):
        code = (gids // stride) % radix
        ok = code < dom  # slot `dom` encodes NULL
        out.append((code.astype(jnp.int32), ok))
    return out


def _gather_batch(b: Batch) -> Batch:
    return Batch(
        {s: (_agather(v), _agather(ok)) for s, (v, ok) in b.lanes.items()},
        _agather(b.sel),
        b.ordered,
        replicated=True,
    )


class MeshExecutor(LocalExecutor):
    """Executes a logical plan SPMD over all mesh devices."""

    def __init__(self, catalogs: CatalogManager, mesh: Optional[Mesh] = None,
                 config: Optional[dict] = None):
        super().__init__(catalogs, config)
        self.mesh = mesh or default_mesh()
        # supervisor identity of each mesh position: default_mesh takes
        # the first n jax devices, so position i IS supervisor device i
        self._mesh_device_ids = list(range(self.mesh.devices.size))
        self.mesh_tasks: List[dict] = []

    # ------------------------------------------------------------------
    def execute(self, plan: P.PlanNode) -> Page:
        assert isinstance(plan, P.Output)
        sup = self.supervisor
        if not self._device_fallback:
            for d in list(self._mesh_device_ids):
                sup.maybe_probe(device_id=d)
            self._shrink_to_healthy()
            if not any(
                sup.healthy(device_id=d) for d in self._mesh_device_ids
            ):
                # every mesh device is out: same degrade/refuse gate as
                # the single-device executor
                bc = Breadcrumb(
                    "mesh:%d/pre-dispatch" % self.mesh.devices.size,
                    query_id=self.query_id,
                    task_id=str(self.config.get("task_id") or ""),
                    mode="gate",
                )
                fault = DeviceFaultError(
                    "device_"
                    + sup.device_state(
                        device_id=self._mesh_device_ids[0]
                    ).lower(),
                    bc,
                )
                if not self._cpu_fallback_enabled():
                    raise fault
                return self._run_cpu_fallback(plan, fault)
        try:
            return self._execute_mesh(plan)
        except DeviceFaultError as fault:
            if self._device_fallback:
                raise
            # a device faulted mid-query and the supervisor quarantined
            # it: shrink the mesh to the healthy subset and re-run there
            # (fewer, larger shards) before degrading all the way to CPU
            if self._shrink_to_healthy():
                try:
                    return self._execute_mesh(plan)
                except DeviceFaultError:
                    pass
            if not self._cpu_fallback_enabled():
                raise
            return self._run_cpu_fallback(plan, fault)

    # ------------------------------------------------------------------
    def _shrink_to_healthy(self) -> bool:
        """Drop quarantined/blacklisted devices from the mesh so the
        query keeps executing over the healthy subset instead of
        failing — a lost shard costs parallelism, not the query.
        Returns True when the mesh changed (the caller then re-shards
        scans over the smaller mesh)."""
        sup = self.supervisor
        ids = list(self._mesh_device_ids)
        healthy = [d for d in ids if sup.healthy(device_id=d)]
        if not healthy or len(healthy) == len(ids):
            return False
        from ..obs import journal

        by_id = dict(zip(ids, list(self.mesh.devices.flat)))
        for d in ids:
            if d in healthy:
                continue
            journal.emit(
                journal.MESH_SHRINK,
                query_id=self.query_id,
                severity=journal.WARN,
                deviceId=d,
                deviceState=sup.device_state(device_id=d),
                fromSize=len(ids),
                toSize=len(healthy),
            )
        self.kernel_profile["meshShrinks"] = (
            self.kernel_profile.get("meshShrinks", 0)
            + (len(ids) - len(healthy))
        )
        self.mesh = Mesh(np.array([by_id[d] for d in healthy]), (AXIS,))
        self._mesh_device_ids = healthy
        return True

    # ------------------------------------------------------------------
    def _run_cpu_fallback(self, plan: P.PlanNode, fault) -> Page:
        # the SPMD program pins explicit mesh devices, so re-running it
        # under jax.default_device would still target the faulted chips;
        # degrade to the single-device executor's eager CPU path instead
        local = LocalExecutor(self.catalogs, dict(self.config))
        local.query_id = self.query_id
        page = local._run_cpu_fallback(plan, fault)
        self.kernel_profile.update(local.kernel_profile)
        self.node_stats.update(getattr(local, "node_stats", {}) or {})
        self.scan_bytes = getattr(local, "scan_bytes", self.scan_bytes)
        return page

    # ------------------------------------------------------------------
    def _dispatch(self, thunk, bc):
        if self._device_fallback:
            return thunk()
        return self.supervisor.dispatch(
            thunk, bc, device_id=self._mesh_device_ids[0]
        )

    def _device_get(self, objs, bc):
        if self._device_fallback:
            return jax.device_get(objs)  # dispatch-guard: ok
        return self.supervisor.device_get(
            objs, bc, device_id=self._mesh_device_ids[0]
        )

    def _record_kernel(self, digest, compile_s, cached, mode="jit",
                       cause=None):
        # every mesh-path kernel record carries the axis-size tag, so
        # flight records, the bandwidth ledger, and bench profiles can
        # tell 8-way from single-chip executions of the same plan
        tag = "mesh:%d" % self.mesh.devices.size
        if not str(digest).startswith("mesh:"):
            digest = "%s/%s" % (tag, digest)
        return super()._record_kernel(digest, compile_s, cached,
                                      mode=mode, cause=cause)

    def _ledger_input_bytes(self, scans) -> int:
        # mesh scan args are flat {sym: [ndev, cap]} ndarray dicts (the
        # $ok validity plane is its own entry), not (value, ok) tuples
        total = 0
        for arrays in scans.values():
            for v in arrays.values():
                total += int(getattr(v, "nbytes", 0) or 0)
        return total

    # ------------------------------------------------------------------
    def _execute_mesh(self, plan: P.PlanNode) -> Page:
        t_exec0 = time.perf_counter()
        self.mesh_tasks = []
        ndev = self.mesh.devices.size
        scan_args, counts_args, dicts = self._load_sharded_scans(plan, ndev)
        self.dicts = dicts
        # skew pre-pass: measure each partitioned join key's real bucket
        # load on the HOST arrays before tracing, so the shuffle chunk is
        # sized for the observed skew up front instead of discovered by
        # whole-fragment recompile rungs (weak #8: the recompile spiral)
        self.shuffle_hints = self._skew_shuffle_hints(
            plan, scan_args, counts_args, ndev
        )
        self.group_capacity = int(self.config.get("group_capacity", 4096))
        self.join_factor = 1
        self.force_expansion = set()
        self.force_no_direct = set()
        self.group_salt = 0
        self.topn_factor = 1
        self.force_wide_mul = False

        for attempt in range(7):
            # class-attribute hook (LocalExecutor.trace_ctx_cls idiom):
            # the cross-host slice executor swaps in _SliceTraceCtx
            ctx = self.mesh_trace_ctx_cls(self, None, None)

            def fragment(scans, counts):
                ctx.scans = scans
                ctx.counts = counts
                batch = ctx.visit(plan.source)
                if not batch.replicated:
                    batch = _gather_batch(batch)
                out = {s: batch.lanes[s] for s in plan.symbols}
                return (
                    out,
                    batch.sel,
                    tuple(ctx.capacity_checks),
                    tuple(d for _, d in ctx.dup_checks),
                    tuple(ctx.collision_checks),
                    tuple(
                        jax.lax.psum(w, AXIS)
                        for w in ctx.lowering.overflow_flags
                    ),
                    tuple(
                        jax.lax.psum(sv, AXIS) for sv in ctx.sum_overflow
                    ),
                )

            shard_fn = _shard_map(
                fragment, self.mesh, (P_(AXIS), P_(AXIS)), P_()
            )
            digest = "mesh:%d/fragment-a%d" % (ndev, attempt)
            compile_start = time.time()
            bc = self._dispatch_crumb(digest, "mesh", scan_args)
            self._last_crumb = bc
            # mesh compiles fresh each attempt (no executable cache):
            # attempt 0 classifies by family warmth, later attempts are
            # ladder rungs — same taxonomy as the jit path
            family = "mesh%d:%s" % (ndev, self._compile_family(plan))
            scan_rows = [
                int(r)
                for c in counts_args.values()
                for r in np.asarray(c).reshape(-1)
            ]
            actual_rows = sum(scan_rows)
            padded_rows = sum(
                int(np.prod(v.shape))
                for arrays in scan_args.values()
                for v in list(arrays.values())[:1]
            )
            shape_sig = self._compile_shape_sig({
                nid: int(np.max(np.asarray(c))) if len(
                    np.asarray(c).reshape(-1)
                ) else 0
                for nid, c in counts_args.items()
            })
            shapes = _shape_summary(scan_args)
            cause = _compile_obs.get_observatory().classify(
                family, shape_sig, ladder_attempt=attempt,
                query_id=self.query_id,
            )
            with TRACER.span(
                "xla_compile", fragment=digest, cause=cause,
                shapeSig=";".join(
                    "%s=%s" % kv for kv in sorted(shapes.items())
                ),
                actualRows=actual_rows, paddedRows=padded_rows,
                paddedRatio=round(
                    padded_rows / actual_rows, 3
                ) if actual_rows else 1.0,
            ):
                fn = jax.jit(shard_fn)  # dispatch-guard: ok (lazy wrapper)
                led_t0 = time.perf_counter()
                out = self._dispatch(
                    lambda: fn(scan_args, counts_args), bc
                )
            self._ledger_bracket(out, digest, "mesh", plan, scan_args,
                                 led_t0)
            compile_s = time.time() - compile_start
            _compile_obs.record_compile(
                kernel=digest, family=family, cause=cause,
                mode="mesh", shapes=shapes, shape_sig=shape_sig,
                actual_rows=actual_rows, padded_rows=padded_rows,
                compile_wall_s=compile_s,
                query_id=self.query_id,
                task_id=str(self.config.get("task_id") or ""),
                node_id=str(self.config.get("node_id") or ""),
                scan_rows=scan_rows,
            )
            self._record_kernel(
                digest, compile_s=compile_s,
                cached=False, mode="mesh", cause=cause,
            )
            # one supervised transfer covers every retry-ladder check
            (checks, dups, colls, wides, sflags) = self._device_get(
                out[2:], self._dispatch_crumb(digest, "device_get")
            )
            out_lanes, sel = out[0], out[1]
            fell_back = False
            for (join_node, _), d in zip(ctx.dup_checks, dups):
                if int(d) > 0:
                    if (
                        getattr(join_node, "direct_domain", None)
                        is not None
                        and id(join_node) not in self.force_no_direct
                    ):
                        # direct-table proof failed: sorted unique first
                        self.force_no_direct.add(id(join_node))
                    else:
                        # duplicate/colliding build keys: re-trace this
                        # join with the many-to-many expansion kernel
                        self.force_expansion.add(id(join_node))
                    fell_back = True
            for cv in colls:
                if int(cv) > 0:
                    self.group_salt += 1
                    fell_back = True
            for wv in wides:
                if int(wv) > 0 and not self.force_wide_mul:
                    self.force_wide_mul = True
                    fell_back = True
            if fell_back:
                continue
            over_kinds = set()
            for n, (cap, kind) in zip(checks, ctx.capacity_limits):
                if int(n) > cap:
                    over_kinds.add(kind)
            if not over_kinds:
                # only a settled attempt may raise (capacity/collision
                # retries make the shadow flag spurious)
                for sv in sflags:
                    if int(sv) > 0:
                        raise ExecutionError(
                            "sum overflows the bigint accumulator"
                        )
                break
            if "group" in over_kinds:
                self.group_capacity *= 8
            if "join" in over_kinds:
                self.join_factor *= 8
            if "topn" in over_kinds:
                self.topn_factor *= 8
        else:
            raise ExecutionError("group capacity overflow after retries")

        # settle-time accounting: the local executor fills these during
        # scan loading / profile finalize, neither of which runs on the
        # mesh path — without them the bench reports 0 scan bytes and
        # the per-shard GB/s satellite has nothing to divide
        self.scan_bytes = self._ledger_input_bytes(scan_args)
        led = self.bandwidth_ledger
        if led is not None:
            s = led.summary()
            self.kernel_profile["bandwidth"] = led.entries()
            self.kernel_profile.setdefault("summary", {}).update(
                effectiveGbps=s["effectiveGbps"],
                rooflinePct=s["rooflinePct"],
                ledgerBytes=s["totalBytes"],
                deviceWallS=s["deviceWallS"],
                meshDevices=ndev,
                perShardGbps=round(s["effectiveGbps"] / ndev, 6),
            )

        page = self._materialize(plan, out_lanes, sel, ctx.ordered_out)
        if self.config.get("collect_node_stats"):
            self._mesh_node_stats(
                plan, scan_args, counts_args,
                time.perf_counter() - t_exec0, ndev, page,
            )
        return page

    # ------------------------------------------------------------------
    def _mesh_node_stats(self, plan, scans, counts, wall_s, ndev, page):
        """Post-execute operator/task stats for the SPMD program.

        The eager per-node row probes cannot run inside shard_map (the
        counts are traced there), so the mesh synthesizes its timeline
        after the program settles: whole-plan node stats feeding
        frames_from_plan, plus one task rollup PER SHARD so EXPLAIN
        ANALYZE stage timelines and the straggler detector see shards.
        Per-shard wall is not separately observable inside one lockstep
        SPMD program; each shard's wall is scaled by its scan-row share
        relative to the heaviest shard — the slowest shard sets the
        program wall and lighter shards idle, which is exactly the data
        skew the straggler detector should surface."""
        from ..obs import opstats

        shard_rows = np.zeros(ndev, dtype=np.int64)
        total_rows = 0
        total_bytes = 0

        def walk(n):
            nonlocal total_rows, total_bytes
            if isinstance(n, P.TableScan):
                cnts = counts.get(str(id(n)))
                arrays = scans.get(str(id(n))) or {}
                nbytes = sum(
                    int(getattr(v, "nbytes", 0) or 0)
                    for v in arrays.values()
                )
                rows = int(cnts.sum()) if cnts is not None else 0
                total_rows += rows
                total_bytes += nbytes
                if cnts is not None:
                    for d in range(min(ndev, len(cnts))):
                        shard_rows[d] += int(cnts[d])
                self.node_stats[id(n)] = {
                    "rows": rows,
                    "bytes": nbytes,
                    "wall_s": 0.0,
                    "device_wall_s": 0.0,
                    "calls": ndev,
                }
            for s in n.sources:
                walk(s)

        walk(plan)
        out_bytes = sum(
            int(getattr(c.values, "nbytes", 0) or 0) for c in page.columns
        )
        # the fragment root carries the whole program wall (walls are
        # inclusive; frames_from_plan subtracts child walls for own-wall)
        self.node_stats[id(plan.source)] = {
            "rows": int(page.count),
            "bytes": out_bytes,
            "wall_s": float(wall_s),
            "device_wall_s": float(wall_s),
            "calls": 1,
        }
        frames = opstats.frames_from_plan(plan, self.node_stats)
        qid = self.query_id or "query"
        heaviest = int(shard_rows.max()) if ndev else 0
        total = int(shard_rows.sum())
        tasks = []
        for d in range(ndev):
            frac = (int(shard_rows[d]) / heaviest) if heaviest else 1.0
            share = (int(shard_rows[d]) / total) if total else 1.0 / ndev
            fl = []
            for f in frames:
                g = dict(f)
                for k in ("inputRows", "inputBytes", "outputRows",
                          "outputBytes"):
                    if k in g:
                        g[k] = int((f.get(k) or 0) * share)
                for k in ("wallS", "deviceWallS", "hostWallS"):
                    if k in g:
                        g[k] = float(f.get(k) or 0.0) * frac
                fl.append(g)
            tasks.append({
                "taskId": "%s.0.%d" % (qid, d),
                "nodeId": "device-%d" % self._mesh_device_ids[d],
                "operatorStats": opstats.task_rollup(
                    fl, wall_s=float(wall_s) * frac
                ),
            })
        self.mesh_tasks = tasks

    # ------------------------------------------------------------------
    def _skew_shuffle_hints(self, plan, scans, counts, ndev):
        """Per (join-node, side) shuffle-chunk capacities measured on the
        host scan arrays: bucket every traceable single-column join key
        with the SAME splitmix the device shuffle uses and record the
        worst per-(sender, destination) load.  Filters below the join
        only remove rows, so the measurement is a safe overestimate; the
        capacity ladder remains the backstop for untraceable keys.

        Reference analog: SkewedPartitionRebalancer's observed-load
        sizing, applied to the mesh all_to_all instead of writer tasks."""
        from .shuffle import mix64_np

        hints: Dict[Tuple[int, str], int] = {}

        def scan_col(node, sym):
            while True:
                if isinstance(node, P.Filter):
                    node = node.source
                    continue
                if isinstance(node, P.Project):
                    nxt = None
                    for s, e in node.assignments:
                        if s == sym:
                            if isinstance(e, ir.ColumnRef):
                                nxt = e.name
                            break
                    if nxt is None:
                        return None
                    sym, node = nxt, node.source
                    continue
                if isinstance(node, P.TableScan):
                    return node, sym
                return None

        def measure(side, sym):
            t = scan_col(side, sym)
            if t is None:
                return None
            scan_node, ssym = t
            merged = scans.get(str(id(scan_node)))
            if merged is None or ssym not in merged:
                return None
            arr = merged[ssym]
            lens = counts.get(str(id(scan_node)))
            if arr.ndim != 2 or arr.dtype.kind not in "iu":
                return None
            worst = 0
            for d in range(arr.shape[0]):
                n = int(lens[d]) if lens is not None else arr.shape[1]
                # count EVERY row, null keys included: the device buckets
                # by the residual value lane regardless of validity (and
                # sides that drop nulls before shuffling just make this a
                # safe overestimate)
                v = arr[d, :n]
                if len(v) == 0:
                    continue
                b = (mix64_np(v.astype(np.int64)) % np.uint64(ndev))
                worst = max(worst, int(np.bincount(
                    b.astype(np.int64), minlength=ndev
                ).max()))
            if worst == 0:
                return None
            return self.ladder.quantize(max(128, int(worst * 1.3)))

        def _wide_key(node, sym):
            t = node.output_types().get(sym)
            return bool(getattr(t, "wide", False))

        def walk(n):
            if (
                isinstance(n, P.Join)
                and len(n.criteria) == 1
                # only the partitioned path reads the hint; measuring
                # broadcast joins would put O(rows) host hashing on the
                # critical path for nothing
                and n.distribution == "partitioned"
            ):
                l, r = n.criteria[0]
                # wide (two-limb) keys force JOINT composite hashing on
                # the device — a raw-value host measurement would use a
                # different bucket permutation
                if not (_wide_key(n.left, l) or _wide_key(n.right, r)):
                    h = measure(n.left, l)
                    if h is not None:
                        hints[(id(n), "l")] = h
                    h = measure(n.right, r)
                    if h is not None:
                        hints[(id(n), "r")] = h
            if isinstance(n, P.SemiJoin) and len(n.source_keys) == 1:
                if not (
                    _wide_key(n.source, n.source_keys[0])
                    or _wide_key(n.filtering, n.filtering_keys[0])
                ):
                    h = measure(n.source, n.source_keys[0])
                    if h is not None:
                        hints[(id(n), "l")] = h
                    h = measure(n.filtering, n.filtering_keys[0])
                    if h is not None:
                        hints[(id(n), "r")] = h
            for s in n.sources:
                walk(s)

        try:
            walk(plan)
        except Exception:
            return {}
        return hints

    # ------------------------------------------------------------------
    def _load_sharded_scans(self, plan: P.PlanNode, ndev: int):
        scans: Dict[str, Dict[str, np.ndarray]] = {}
        counts: Dict[str, np.ndarray] = {}
        dicts: Dict[str, np.ndarray] = {}
        # preorder TableScan index: the same ordinal FragmentExecutor's
        # _load_walk uses as the scheduler's split-assignment key, so the
        # cross-host subclass can look up its ASSIGNED splits
        scan_idx = [0]

        def walk(node: P.PlanNode):
            if isinstance(node, P.TableScan):
                idx = scan_idx[0]
                scan_idx[0] += 1
                conn = self.catalogs.get(node.catalog)
                cols = [c for _, c in node.assignments]
                provider = conn.page_source_provider()
                sym_of = {c: self._sym_for(node, c) for c in cols}
                symbols = [sym_of[c] for c in cols]
                tmap = dict(node.types)
                types = [(s, tmap[s]) for s in symbols]
                splits = self._scan_splits(node, idx, ndev)
                per_dev: List[Dict[str, tuple]] = []
                per_dev_dicts: List[Dict[str, np.ndarray]] = []
                dev_counts: List[int] = []
                for d in range(ndev):
                    pages = []
                    for sp in splits[d::ndev]:
                        src = provider.create_page_source(sp, cols)
                        for page in src.pages():
                            src_dicts = src.dictionaries()
                            new_cols = [
                                Column(
                                    col.type, col.values, col.validity,
                                    col.dictionary
                                    if col.dictionary is not None
                                    else src_dicts.get(c),
                                )
                                for c, col in zip(page.names, page.columns)
                            ]
                            pages.append(
                                Page(new_cols, page.count,
                                     [sym_of[c] for c in page.names])
                            )
                    ddicts: Dict[str, np.ndarray] = {}
                    merged_d, total = merge_pages_to_arrays(
                        pages, symbols, types, ddicts
                    )
                    per_dev.append(merged_d)
                    per_dev_dicts.append(ddicts)
                    dev_counts.append(total)
                self._merge_split_dicts(per_dev, per_dev_dicts, dicts)
                for s, t in types:
                    if t.is_dictionary and s not in dicts:
                        dicts[s] = np.array([], dtype=object)
                cap = self.ladder.quantize(max(max(dev_counts), 1))
                merged: Dict[str, np.ndarray] = {}
                for c in cols:
                    sym = sym_of[c]
                    stacked = np.zeros(
                        (ndev, cap), dtype=per_dev[0][sym][0].dtype
                    )
                    okstack = np.zeros((ndev, cap), dtype=bool)
                    for d in range(ndev):
                        v, ok = per_dev[d][sym]
                        stacked[d, : dev_counts[d]] = v
                        okstack[d, : dev_counts[d]] = (
                            np.ones(dev_counts[d], dtype=bool)
                            if ok is None else ok
                        )
                    merged[sym] = stacked
                    merged[sym + "$ok"] = okstack
                scans[str(id(node))] = merged
                counts[str(id(node))] = np.array(dev_counts, dtype=np.int64)
                return
            if isinstance(node, P.RemoteSource):
                self._load_remote_source(node, ndev, scans, counts, dicts)
                return
            for s in node.sources:
                walk(s)

        walk(plan)
        return scans, counts, dicts

    def _scan_splits(self, node: P.TableScan, idx: int, ndev: int):
        """All of a table's splits — this executor owns the whole mesh.
        The cross-host subclass narrows this to the splits the
        coordinator assigned to THIS host's task (split assignment
        happened one level up, across hosts)."""
        conn = self.catalogs.get(node.catalog)
        # real connector splits (hive files/row groups, tpch shards)
        # round-robin over devices — the NodeScheduler split
        # placement, with devices standing in for worker nodes
        return conn.split_manager().get_splits(
            node.table, ndev, node.constraint
        )

    def _load_remote_source(self, node, ndev, scans, counts, dicts):
        # single-process mesh plans have no exchanges inside them; only
        # the cross-host slice executor (which overrides this) feeds
        # fragments containing RemoteSource nodes
        raise ExecutionError(
            "mesh executor cannot read remote sources"
        )

    def _merge_split_dicts(self, per_dev, per_dev_dicts, dicts):
        """Unify per-device varchar dictionaries across the mesh: build one
        union dictionary per symbol and remap each device's codes into it
        (the cross-task DictionaryBlock unification that
        exec/local.py merge_pages_to_arrays performs within one task —
        real hive tables carry per-file dictionaries, so devices holding
        different files legitimately diverge)."""
        all_syms = set()
        for dd in per_dev_dicts:
            all_syms.update(dd)
        for sym in all_syms:
            present = [dd.get(sym) for dd in per_dev_dicts]
            base = next((d for d in present if d is not None), None)
            if all(
                d is None or d is base or np.array_equal(d, base)
                for d in present
            ):
                dicts[sym] = base
                continue
            index: Dict[str, int] = {}
            entries: List[str] = []
            for dev, d in enumerate(present):
                if d is None:
                    continue
                remap = np.empty(len(d), dtype=np.int32)
                for i, s in enumerate(d):
                    s = str(s)
                    if s not in index:
                        index[s] = len(entries)
                        entries.append(s)
                    remap[i] = index[s]
                codes, ok = per_dev[dev][sym]
                safe = np.clip(codes, 0, max(len(d) - 1, 0))
                per_dev[dev][sym] = (
                    np.where(codes >= 0, remap[safe], -1).astype(codes.dtype),
                    ok,
                )
            dicts[sym] = np.array(entries, dtype=object)


class _MeshTraceCtx(_TraceCtx):
    """Trace context inside shard_map: exchange points become collectives."""

    # compaction capacities are GLOBAL row estimates; a mesh shard holds
    # 1/ndev of the rows (and skew could overflow a shard-scaled guess)
    allow_compaction = False

    def __init__(self, ex: MeshExecutor, scans, counts):
        super().__init__(ex, scans, counts)
        self.capacity_limits: List[int] = []
        self.ordered_out = False

    def _note_capacity(self, ngroups, cap, kind="group"):
        # replicate the check value so it can cross the out_specs=P() boundary
        self.capacity_checks.append(jax.lax.pmax(ngroups, AXIS))
        self.capacity_limits.append((cap, kind))

    def _note_collision(self, coll):
        self.collision_checks.append(jax.lax.pmax(coll, AXIS))

    def visit(self, node: P.PlanNode) -> Batch:
        # the eager per-node instrumentation concretizes row counts
        # (int(jnp.sum(sel))), which is impossible while tracing inside
        # shard_map — the executor synthesizes node stats and per-shard
        # task rollups after the program settles (_mesh_node_stats)
        m = getattr(self, f"_visit_{type(node).__name__.lower()}", None)
        if m is None:
            raise ExecutionError(f"no executor for {type(node).__name__}")
        return m(node)

    def _merge_fused_sums(self, sums):
        """Megakernel shard bodies: merge the per-shard fused
        (term, group) int64 partials across the mesh before the shared
        finalize tail.  all_gather + local reduce rather than psum keeps
        the exchange in the canonical all-gather/dynamic-slice HLO form;
        exactness rides the megakernel's own SUM_GATE proof — the
        TABLE-wide total clears the 2^62 gate, so the cross-shard sum of
        per-shard partials cannot wrap int64."""
        return jax.tree_util.tree_map(
            lambda s: jnp.sum(jax.lax.all_gather(s, AXIS), axis=0), sums
        )

    # -- leaves ---------------------------------------------------------
    def _visit_tablescan(self, node: P.TableScan) -> Batch:
        arrays = self.scans[str(id(node))]
        count = self.counts[str(id(node))][0]
        lanes = {}
        cap = None
        for sym, arr in arrays.items():
            if sym.endswith("$ok"):
                continue
            v = arr[0]  # local shard [1, cap] -> [cap]
            cap = v.shape[0]
            ok = arrays[sym + "$ok"][0]
            lanes[sym] = (v, ok)
        sel = jnp.arange(cap) < count
        return Batch(lanes, sel, replicated=False)

    def _visit_values(self, node: P.Values) -> Batch:
        b = super()._visit_values(node)
        # identical values exist on every device; select only on device 0
        myidx = jax.lax.axis_index(AXIS)
        return Batch(b.lanes, b.sel & (myidx == 0), b.ordered, False)

    # -- aggregation -----------------------------------------------------
    def _visit_aggregate(self, node: P.Aggregate) -> Batch:
        if node.step in ("single", "partial"):
            from ..ops import megakernel

            fused = megakernel.try_fused(self, node)
            if fused is not None:
                # each shard ran the fused kernel over its own split; the
                # _merge_fused_sums collective already made the finished
                # accumulators identical on every device
                return Batch(
                    fused.lanes, fused.sel, fused.ordered, replicated=True
                )
        b = self.visit(node.source)
        all_specs = [a.to_spec() for a in node.aggs]
        collective_able = all(
            s.psum_kind(n) is not None or _is_hll_lane(s, n)
            for s in all_specs
            for n in s.accumulator_names
        )
        hll = any(
            _is_hll_lane(s, n)
            for s in all_specs
            for n in s.accumulator_names
        )
        # strictly psum-able: the global fast path (1-row accumulators)
        psum_able = collective_able and not hll
        raw_needed = any(
            a.distinct or not a.partializable for a in node.aggs
        )
        if not b.replicated and raw_needed and node.keys:
            # grouped DISTINCT / non-decomposable aggregates: FIXED_HASH
            # exchange on the GROUP BY keys co-locates each group's raw
            # rows, then every device aggregates its own hash range
            # exactly — the count(DISTINCT)-beyond-memory path.  The old
            # gathering exchange replicated the ENTIRE input into every
            # device; here no device ever holds more than its hash range
            # (plus skew slack, backstopped by the capacity ladder).
            b = self._hash_repartition(b, tuple(node.keys))
            out = _TraceCtx._visit_aggregate(self, node, b)
            return Batch(out.lanes, out.sel, out.ordered, replicated=False)
        if not b.replicated and (
            raw_needed or (not psum_able and not node.keys)
        ):
            # global DISTINCT / non-decomposable aggregates need the raw
            # rows in one place (a gathered approx_distinct even stays
            # EXACT: the single-step path counts, it never sketches) —
            # and global aggregates whose accumulators no collective can
            # merge (min_by/bitwise/arbitrary) gather instead of psum.
            b = _gather_batch(b)
        if b.replicated:
            out = _TraceCtx._visit_aggregate(self, node, b)
            return Batch(out.lanes, out.sel, out.ordered, replicated=True)
        types = node.source.output_types()
        b, aggs = self._agg_dict_setup(node, b)
        specs = [a.to_spec() for a in aggs]

        if not node.keys:
            gid = jnp.zeros(b.sel.shape[0], dtype=jnp.int64)
            accs = agg_ops.accumulate(
                specs, b.lanes, gid, b.sel, 1,
                overflow_flags=self.sum_overflow,
                wide_flags=self.lowering.overflow_flags,
                force_wide=self.lowering.force_wide_mul,
            )
            accs = self._psum_accs(specs, accs)
            out = agg_ops.finalize(specs, accs)
            from ..ops.wide_decimal import pad_rows

            lanes = {
                k: (pad_rows(v, 127), jnp.pad(ok, (0, 127)))
                for k, (v, ok) in out.items()
            }
            sel = jnp.pad(jnp.ones(1, bool), (0, 127))
            return Batch(lanes, sel, replicated=True)

        key_lanes = [b.lanes[k] for k in node.keys]
        domains = self._direct_domains(node.keys, types)
        if domains is not None and collective_able:
            gid, cap = agg_ops.direct_group_ids(key_lanes, domains)
            accs = agg_ops.accumulate(
                specs, b.lanes, gid, b.sel, cap,
                # sketched approx_distinct must emit its mergeable HLL
                # register lanes here (the single-step shortcut is an
                # exact per-shard count, which cannot merge across
                # shards); plain accumulators are step-invariant
                step="partial" if hll else "single",
                overflow_flags=self.sum_overflow,
                wide_flags=self.lowering.overflow_flags,
                force_wide=self.lowering.force_wide_mul,
            )
            present_local = agg_ops._seg_count(b.sel, gid, cap) > 0
            # exchange: dense accumulators are psum-able (partial->final)
            accs = self._psum_accs(specs, accs)
            present = jax.lax.psum(present_local.astype(jnp.int32), AXIS) > 0
            out = agg_ops.finalize(specs, accs)
            keys_out = _decode_direct_keys(domains, cap)
        else:
            # partial aggregate locally; gathering exchange of partial
            # group state; re-merge (PARTIAL -> exchange -> FINAL)
            cap = min(self.ex.group_capacity, b.sel.shape[0])
            perm, gid, ngroups = self._group_sort(key_lanes, b.sel, cap)
            self._note_capacity(ngroups, cap)
            sel_sorted = b.sel[perm]
            from ..ops.filter_project import permute_lanes

            sorted_lanes = permute_lanes(b.lanes, perm)
            ss = agg_ops.SortedSegments(gid, cap)
            accs = agg_ops.accumulate(
                specs, sorted_lanes, gid, sel_sorted, cap, step="partial",
                overflow_flags=self.sum_overflow,
                wide_flags=self.lowering.overflow_flags,
                force_wide=self.lowering.force_wide_mul,
                seg=ss,
            )
            present_local = jnp.arange(cap) < ngroups
            keys_local = agg_ops.group_keys_output(
                [sorted_lanes[k] for k in node.keys], gid, sel_sorted, cap,
                starts=ss.starts,
            )
            acc_lanes = {
                name: (_agather(arr), jnp.ones(arr.shape[0] * self._ndev(), bool))
                for name, arr in accs.items()
            }
            key_lanes_g = [(_agather(v), _agather(ok)) for v, ok in keys_local]
            present_g = _agather(present_local)
            fcap = min(self.ex.group_capacity, present_g.shape[0])
            perm2, gid2, ngroups2 = self._group_sort(
                key_lanes_g, present_g, fcap
            )
            self._note_capacity(ngroups2, fcap)
            sel2 = present_g[perm2]
            acc_sorted = {
                s: (v[perm2], ok[perm2]) for s, (v, ok) in acc_lanes.items()
            }
            merged = agg_ops.merge_accumulators(
                specs, acc_sorted, gid2, sel2, fcap,
                overflow_flags=self.sum_overflow,
            )
            out = agg_ops.finalize(specs, merged)
            keys_out = agg_ops.group_keys_output(
                [(v[perm2], ok[perm2]) for v, ok in key_lanes_g],
                gid2,
                sel2,
                fcap,
            )
            present = jnp.arange(fcap) < ngroups2
            cap = fcap

        lanes = {}
        for k, kl in zip(node.keys, keys_out):
            lanes[k] = kl
        for s in out:
            lanes[s] = out[s]
        pad_cap = self.ex.ladder.quantize(cap)
        if pad_cap != cap:
            from ..ops.wide_decimal import pad_rows

            lanes = {
                s: (
                    pad_rows(v, pad_cap - cap),
                    jnp.pad(ok, (0, pad_cap - cap)),
                )
                for s, (v, ok) in lanes.items()
            }
            present = jnp.pad(present, (0, pad_cap - cap))
        return Batch(lanes, present, replicated=True)

    def _ndev(self) -> int:
        return self.ex.mesh.devices.size

    def _psum_accs(self, specs, accs):
        """Cross-device accumulator merge by collective; callers must have
        checked psum_kind != None (or the HLL-lane exception) for every
        accumulator first.  int64 sum accumulators get an f64 shadow psum
        so a cross-device wrap (each shard under the threshold, total
        beyond int64) fails loudly."""
        out = {}
        ops = {"sum": jax.lax.psum, "min": jax.lax.pmin, "max": jax.lax.pmax}
        for s in specs:
            hll_names = [
                n for n in s.accumulator_names if _is_hll_lane(s, n)
            ]
            if hll_names:
                # HLL sketches union by ELEMENTWISE register max — a max
                # of the packed int64 words would compare the 8-register
                # concatenation lexicographically, which is wrong
                cap = accs[hll_names[0]].shape[0]
                lanes = {i: accs[n] for i, n in enumerate(hll_names)}
                merged = sketches.hll_pmax_merge(lanes, cap, AXIS)
                for i, n in enumerate(hll_names):
                    out[n] = merged[i]
            for name in s.accumulator_names:
                if _is_hll_lane(s, name):
                    continue
                kind = s.psum_kind(name)
                out[name] = ops[kind](accs[name], AXIS)
                if (
                    kind == "sum"
                    and s.kind in ("sum", "avg")
                    and accs[name].dtype == jnp.int64
                    and (name.endswith("$val") or name.endswith("$sum"))
                ):
                    shadow = jax.lax.psum(
                        accs[name].astype(jnp.float64), AXIS
                    )
                    self.sum_overflow.append(
                        jnp.sum(jnp.abs(shadow) > 9.0e18).astype(jnp.int64)
                    )
        return out

    # -- joins ----------------------------------------------------------
    def _visit_join(self, node: P.Join) -> Batch:
        left = self.visit(node.left)
        right = self.visit(node.right)
        if self._use_partitioned(node, left, right):
            return self._partitioned_join(node, left, right)
        if not right.replicated:
            # broadcast exchange: replicate build side to all workers
            right = _gather_batch(right)
        out = self._join_batches(node, left, right)
        out.replicated = left.replicated
        return out

    def _hinted_chunk(self, node, side, cap, ndev, factor):
        """Shuffle-chunk capacity: the host-measured skew hint when one
        exists (grown by the ladder factor as the backstop), else the
        2x-slack default."""
        h = getattr(self.ex, "shuffle_hints", {}).get((id(node), side))
        q = self.ex.ladder.quantize
        if h is not None:
            return min(q(h * factor), q(max(128, cap)))
        return _shuffle_chunk(cap, ndev, factor, quantize=q)

    def _use_partitioned(self, node: P.Join, left: Batch, right: Batch):
        """The DetermineJoinDistributionType decision at execution time:
        honor the optimizer's choice when present, else fall back to a
        capacity heuristic (broadcasting a build side bigger than the
        threshold would replicate it into every device's HBM)."""
        if (
            node.kind not in ("inner", "left")
            or not node.criteria
            or left.replicated
            or right.replicated
        ):
            return False
        if node.distribution == "partitioned":
            return True
        if node.distribution == "broadcast":
            return False
        return self._exceeds_broadcast_threshold(right)

    def _partitioned_join(self, node: P.Join, left: Batch, right: Batch):
        """HASH-HASH distribution: all-to-all both sides on the join keys,
        then join locally — each device owns one hash range of the key
        space (PartitionedLookupSourceFactory / FIXED_HASH exchange pair).
        NULL-key probe rows of an outer join are retained (routed by the
        garbage hash, they match nothing but must still emit)."""
        ndev = self._ndev()
        factor = getattr(self.ex, "join_factor", 1)
        lkeys = [left.lanes[l] for l, _ in node.criteria]
        rkeys = [right.lanes[r] for _, r in node.criteria]
        joint = join_ops.needs_verification(
            lkeys
        ) or join_ops.needs_verification(rkeys)
        lbuck, lok = shuffle.bucket_of(lkeys, left.sel, ndev, joint)
        rbuck, rok = shuffle.bucket_of(rkeys, right.sel, ndev, joint)
        lkeep = left.sel & (lok | (node.kind == "left"))
        rkeep = right.sel & rok
        lchunk = self._hinted_chunk(node, "l", left.sel.shape[0], ndev,
                                    factor)
        rchunk = self._hinted_chunk(node, "r", right.sel.shape[0], ndev,
                                    factor)
        llanes, lsel, lmax = shuffle.repartition(
            left.lanes, left.sel, lbuck, lkeep, ndev, lchunk, AXIS
        )
        rlanes, rsel, rmax = shuffle.repartition(
            right.lanes, right.sel, rbuck, rkeep, ndev, rchunk, AXIS
        )
        self._note_capacity(lmax, lchunk, "join")
        self._note_capacity(rmax, rchunk, "join")
        out = self._join_batches(
            node,
            Batch(llanes, lsel, replicated=False),
            Batch(rlanes, rsel, replicated=False),
        )
        out.replicated = False
        return out

    def _visit_semijoin(self, node: P.SemiJoin) -> Batch:
        src = self.visit(node.source)
        filt = self.visit(node.filtering)
        if (
            not src.replicated
            and not filt.replicated
            and node.filter is None
            and self._semi_use_partitioned(filt)
        ):
            return self._partitioned_semijoin(node, src, filt)
        if not filt.replicated:
            # broadcast the filtering side (dynamic-filter style exchange)
            filt = _gather_batch(filt)
        hit = self._semi_hit(node, src, filt)
        lanes = dict(src.lanes)
        lanes[node.output] = (hit, jnp.ones(hit.shape, bool))
        return Batch(lanes, src.sel, src.ordered, src.replicated)

    def _semi_use_partitioned(self, filt: Batch) -> bool:
        return self._exceeds_broadcast_threshold(filt)

    def _exceeds_broadcast_threshold(self, build: Batch) -> bool:
        from ..config import BROADCAST_JOIN_THRESHOLD_ROWS

        threshold = int(
            self.ex.config.get(
                "broadcast_join_threshold_rows",
                BROADCAST_JOIN_THRESHOLD_ROWS,
            )
        )
        # shape[0] is the per-device shard capacity; the threshold is
        # total build rows, so broadcasting replicates ndev * shape[0]
        return build.sel.shape[0] * self._ndev() >= threshold

    def _partitioned_semijoin(
        self, node: P.SemiJoin, src: Batch, filt: Batch
    ) -> Batch:
        """HASH-HASH semi join: repartition BOTH sides on the semi keys
        and mark locally per hash range (the reference's partitioned
        SemiJoinNode distribution).  NULL-key source rows route to a
        stable device (they match nothing but must still emit their
        mark=false row); the output stays distributed."""
        ndev = self._ndev()
        skeys = [src.lanes[k] for k in node.source_keys]
        fkeys = [filt.lanes[k] for k in node.filtering_keys]
        joint = join_ops.needs_verification(
            skeys
        ) or join_ops.needs_verification(fkeys)
        sbuck, sok = shuffle.bucket_of(skeys, src.sel, ndev, joint)
        fbuck, fok = shuffle.bucket_of(fkeys, filt.sel, ndev, joint)
        sbuck = jnp.where(sok, sbuck, 0)
        factor = getattr(self.ex, "join_factor", 1)
        schunk = self._hinted_chunk(node, "l", src.sel.shape[0], ndev,
                                    factor)
        fchunk = self._hinted_chunk(node, "r", filt.sel.shape[0], ndev,
                                    factor)
        slanes, ssel, smax = shuffle.repartition(
            src.lanes, src.sel, sbuck, src.sel, ndev, schunk, AXIS
        )
        flanes, fsel, fmax = shuffle.repartition(
            filt.lanes, filt.sel, fbuck, filt.sel & fok, ndev, fchunk, AXIS
        )
        self._note_capacity(smax, schunk, "join")
        self._note_capacity(fmax, fchunk, "join")
        src2 = Batch(slanes, ssel, replicated=False)
        filt2 = Batch(flanes, fsel, replicated=False)
        hit = self._semi_hit(node, src2, filt2)
        lanes = dict(src2.lanes)
        lanes[node.output] = (hit, jnp.ones(hit.shape, bool))
        return Batch(lanes, src2.sel, replicated=False)

    def _visit_scalarjoin(self, node: P.ScalarJoin) -> Batch:
        src = self.visit(node.source)
        sub = self.visit(node.subquery)
        if not sub.replicated:
            sub = _gather_batch(sub)
        first = jnp.argmax(sub.sel)
        n = src.sel.shape[0]
        lanes = dict(src.lanes)
        for s, (v, ok) in sub.lanes.items():
            val = v[first]
            okv = ok[first] & (sub.sel.sum() > 0)
            lanes[s] = (
                jnp.broadcast_to(val, (n,)),
                jnp.broadcast_to(okv, (n,)),
            )
        return Batch(lanes, src.sel, src.ordered, src.replicated)

    def _hash_repartition(self, b: Batch, key_syms) -> Batch:
        """FIXED_HASH exchange of a distributed batch by key columns —
        rows with equal keys co-locate (AddExchanges partitioned
        distribution for window/distinct/set ops)."""
        ndev = self._ndev()
        key_lanes = [b.lanes[s] for s in key_syms]
        bucket, kok = shuffle.bucket_of(key_lanes, b.sel, ndev)
        # NULL keys form their own group: bucket_of hashes value lanes
        # only, so route invalid-key rows to a stable device (0)
        bucket = jnp.where(kok, bucket, 0)
        chunk = _shuffle_chunk(
            b.sel.shape[0], ndev, getattr(self.ex, "join_factor", 1),
            quantize=self.ex.ladder.quantize,
        )
        lanes, sel, mx = shuffle.repartition(
            b.lanes, b.sel, bucket, b.sel, ndev, chunk, AXIS
        )
        self._note_capacity(mx, chunk, "join")
        return Batch(lanes, sel, replicated=False)

    # -- window ----------------------------------------------------------
    def _visit_window(self, node: P.Window) -> Batch:
        """Partitioned windows hash-repartition by the PARTITION BY keys
        (AddExchanges.java:138 window partitioning) and window locally;
        only partition-less windows need the gathering exchange."""
        b = self.visit(node.source)
        part_keys = tuple(node.partition_by)
        if not b.replicated and part_keys:
            b = self._hash_repartition(b, part_keys)
            replicated_out = False
        elif not b.replicated:
            b = _gather_batch(b)
            replicated_out = True
        else:
            replicated_out = True
        saved_visit = self.visit

        def patched_visit(n):
            return b if n is node.source else saved_visit(n)

        self.visit = patched_visit
        try:
            out = _TraceCtx._visit_window(self, node)
        finally:
            self.visit = saved_visit
        out.replicated = replicated_out
        return out

    # -- ordering --------------------------------------------------------
    def _visit_sort(self, node: P.Sort) -> Batch:
        """Distributed sort = RANGE exchange on the leading key + local
        sort per device: device order concatenates into the total order,
        so no global gather-then-sort (MergeOperator by placement).
        Replicated inputs keep the plain local sort."""
        b = self.visit(node.source)
        if b.replicated:
            keys = self._rank_sort_keys(node.keys, b)
            perm = sort_ops.sort_perm(keys, b.lanes, b.sel)
            lanes, sel = sort_ops.apply_perm(b.lanes, perm, b.sel)
            self.ordered_out = True
            return Batch(lanes, sel, ordered=True, replicated=True)
        ndev = self._ndev()
        keys = self._rank_sort_keys(node.keys, b)
        lead = keys[0]
        # _rank_sort_keys always registers its (possibly hidden $rank)
        # lane in b.lanes, so the lead column is present by construction
        bucket = shuffle.range_buckets(
            b.lanes[lead.column], lead, b.sel, ndev, AXIS
        )
        chunk = _shuffle_chunk(
            b.sel.shape[0], ndev, getattr(self.ex, "join_factor", 1),
            quantize=self.ex.ladder.quantize,
        )
        lanes, sel, mx = shuffle.repartition(
            b.lanes, b.sel, bucket, b.sel, ndev, chunk, AXIS
        )
        self._note_capacity(mx, chunk, "join")
        b2 = Batch(lanes, sel, replicated=False)
        keys2 = self._rank_sort_keys(node.keys, b2)
        perm = sort_ops.sort_perm(keys2, b2.lanes, b2.sel)
        lanes2, sel2 = sort_ops.apply_perm(b2.lanes, perm, b2.sel)
        self.ordered_out = True
        # device-ordered: the final all_gather (device order preserved)
        # materializes the total order without any further sort
        return Batch(lanes2, sel2, ordered=True, replicated=False)

    def _visit_topn(self, node: P.TopN) -> Batch:
        b = self.visit(node.source)
        keys = self._rank_sort_keys(node.keys, b)
        lanes, sel, check = sort_ops.topn(
            keys, b.lanes, b.sel, node.count,
            getattr(self.ex, 'topn_factor', 1),
        )
        if check is not None:
            self._note_capacity(check[0], check[1], "topn")
        if not b.replicated:
            # local top-n -> gather candidates -> global top-n (MergeOperator)
            b2 = Batch(
                {s: (_agather(v), _agather(ok)) for s, (v, ok) in lanes.items()},
                _agather(sel),
            )
            keys2 = self._rank_sort_keys(node.keys, b2)
            lanes, sel, check2 = sort_ops.topn(
                keys2, b2.lanes, b2.sel, node.count,
                getattr(self.ex, 'topn_factor', 1),
            )
            if check2 is not None:
                self._note_capacity(check2[0], check2[1], "topn")
        self.ordered_out = True
        return Batch(lanes, sel, ordered=True, replicated=True)

    def _visit_limit(self, node: P.Limit) -> Batch:
        b = self.visit(node.source)
        # per-device partial keeps count+offset; the post-gather limit
        # applies the offset skip
        lanes, sel = sort_ops.limit(
            b.lanes, b.sel, node.count + node.offset
        )
        if not b.replicated:
            b2 = Batch(
                {s: (_agather(v), _agather(ok)) for s, (v, ok) in lanes.items()},
                _agather(sel),
            )
            lanes, sel = sort_ops.limit(
                b2.lanes, b2.sel, node.count, node.offset
            )
            return Batch(lanes, sel, replicated=True)
        lanes, sel = sort_ops.limit(lanes, sel, node.count, node.offset)
        return Batch(lanes, sel, b.ordered, b.replicated)

    def _visit_distinct(self, node: P.Distinct) -> Batch:
        b = super()._visit_distinct(node)
        if not b.replicated:
            # FIXED_HASH exchange on the distinct keys: equal rows
            # co-locate, each device dedupes its hash range, and the
            # output STAYS distributed (MarkDistinct partitioned plan)
            b = self._hash_repartition(b, tuple(node.output_symbols()))
            b = self._local_distinct(node.output_symbols(), b)
            b.replicated = False
        return b

    def _local_distinct(self, syms, b: Batch) -> Batch:
        key_lanes = [b.lanes[s] for s in syms]
        cap = b.sel.shape[0]
        perm, gid, _ = self._group_sort(key_lanes, b.sel, cap)
        boundary = jnp.concatenate(
            [jnp.ones(1, dtype=bool), gid[1:] != gid[:-1]]
        )
        lanes = {s: (v[perm], ok[perm]) for s, (v, ok) in b.lanes.items()}
        return Batch(lanes, b.sel[perm] & boundary, replicated=b.replicated)

    def _partitioned_setop(self, node: P.SetOperation) -> Batch:
        """INTERSECT/EXCEPT on the mesh: union the inputs positionally
        (dictionaries merged — so codes are comparable mesh-wide), then
        FIXED_HASH-repartition the tagged rows by the full row value and
        run the tag-mark dedup per device hash range.  Rows from
        replicated inputs are sent by device 0 only (one copy)."""
        if node.all:
            raise ExecutionError(
                f"{node.kind.upper()} ALL not supported (DISTINCT only)"
            )
        assert len(node.inputs) == 2
        batches = [self.visit(i) for i in node.inputs]
        if all(b.replicated for b in batches):
            saved_visit = self.visit
            by_id = {id(i): b for i, b in zip(node.inputs, batches)}
            self.visit = lambda n: by_id.get(id(n)) or saved_visit(n)
            try:
                out = _TraceCtx._visit_setoperation(self, node)
            finally:
                self.visit = saved_visit
            out.replicated = True
            return out
        saved_visit = self.visit
        by_id = {id(i): b for i, b in zip(node.inputs, batches)}
        self.visit = lambda n: by_id.get(id(n)) or saved_visit(n)
        try:
            lanes0, sel, caps = self._union_lanes(node)
        finally:
            self.visit = saved_visit
        tag = jnp.concatenate([
            jnp.full(c, i, dtype=jnp.int32) for i, c in enumerate(caps)
        ])
        # one copy of replicated inputs' rows: only device 0 transmits
        my_dev = jax.lax.axis_index(AXIS)
        rep_row = jnp.concatenate([
            jnp.full(c, b.replicated, dtype=bool)
            for b, c in zip(batches, caps)
        ])
        keep = sel & (~rep_row | (my_dev == 0))
        ndev = self._ndev()
        key_lanes = [lanes0[s] for s in node.symbols]
        bucket, kok = shuffle.bucket_of(key_lanes, sel, ndev)
        bucket = jnp.where(kok, bucket, 0)
        all_lanes = dict(lanes0)
        all_lanes["__tag__"] = (tag, jnp.ones(tag.shape[0], bool))
        chunk = _shuffle_chunk(
            sel.shape[0], ndev, getattr(self.ex, "join_factor", 1),
            quantize=self.ex.ladder.quantize,
        )
        lanes2, sel2, mx = shuffle.repartition(
            all_lanes, sel, bucket, keep, ndev, chunk, AXIS
        )
        self._note_capacity(mx, chunk, "join")
        tag2, _ = lanes2.pop("__tag__")
        out = self._setop_tag_reduce(
            node, lanes2, sel2, tag2, sel2.shape[0]
        )
        out.replicated = False
        return out

    def _visit_setoperation(self, node: P.SetOperation) -> Batch:
        if node.kind in ("intersect", "except"):
            return self._partitioned_setop(node)
        # UNION: gather every non-replicated input, then reuse the local
        # union (ALL keeps the ARBITRARY-exchange path upstream)
        originals = {}
        for inp in node.inputs:
            batch = self.visit(inp)
            if not batch.replicated:
                batch = _gather_batch(batch)
            originals[id(inp)] = batch

        saved_visit = self.visit

        def patched_visit(n):
            if id(n) in originals:
                return originals[id(n)]
            return saved_visit(n)

        self.visit = patched_visit
        try:
            out = _TraceCtx._visit_setoperation(self, node)
        finally:
            self.visit = saved_visit
        out.replicated = True
        return out


class _SliceTraceCtx(_MeshTraceCtx):
    """Trace context for ONE HOST'S slice of a multi-host cluster.

    The mesh here spans only this process's local devices; the global
    exchange between hosts is the server exchange layer (HTTP pages +
    spool), not an XLA collective.  Two consequences:

      - a RemoteSource is a network input this host already fetched: its
        pages were merged once and tiled identically onto every local
        device, so the batch is replicated (the broadcast build side of
        FIXED_BROADCAST_DISTRIBUTION joins)
      - a PARTIAL aggregate must STAY partial: each device emits its
        accumulator rows and the Output gather ships ndev partial rows
        per group through the exchange — the consumer fragment's FINAL
        step merges them exactly as if they came from more tasks.  The
        inherited mesh path would psum/merge to finished values here,
        which double-finalizes once the consumer merges again.
    """

    def _visit_remotesource(self, node: P.RemoteSource) -> Batch:
        b = self._visit_tablescan(node)
        return Batch(b.lanes, b.sel, b.ordered, replicated=True)

    def _visit_aggregate(self, node: P.Aggregate) -> Batch:
        if node.step == "partial":
            # bypass the fused/collective mesh paths (they emit FINALIZED
            # outputs); the plain local partial path emits per-device
            # accumulator lanes, one independent slice per device
            b = self.visit(node.source)
            out = _TraceCtx._visit_aggregate(self, node, b)
            return Batch(
                out.lanes, out.sel, out.ordered, replicated=b.replicated
            )
        return super()._visit_aggregate(node)


# node types a host slice can run SPMD over its local devices.  Sort /
# Window / SetOperation / writers are excluded: they either demand the
# whole input ordered in one place or mutate external state — those
# fragments keep the single-device FragmentExecutor.
_SLICE_NODES = (
    P.Output, P.TableScan, P.RemoteSource, P.Filter, P.Project, P.Values,
    P.Aggregate, P.Join, P.SemiJoin, P.ScalarJoin, P.TopN, P.Limit,
    P.Distinct,
)


def slice_eligible(plan: P.PlanNode) -> bool:
    """True when a fragment can run as a per-host shard_map slice.

    Exactly one TableScan: that makes it a SOURCE fragment whose splits
    the coordinator already partitioned across hosts, and guarantees any
    RemoteSource inputs are broadcast build sides (plan/fragment.py
    places partitioned exchanges only between fragments).  Aggregates
    must be PARTIAL — a final-step merge belongs to the consumer side of
    the network exchange, where the rows from every host meet.
    """
    nscans = 0
    stack = [plan]
    while stack:
        n = stack.pop()
        if not isinstance(n, _SLICE_NODES):
            return False
        if isinstance(n, P.TableScan):
            nscans += 1
        elif isinstance(n, P.Aggregate) and n.step != "partial":
            return False
        stack.extend(n.sources)
    return nscans == 1


class CrossHostFragmentExecutor(MeshExecutor):
    """Runs one fragment task as this host's slice of the global mesh.

    Drop-in for exec.fragment_exec.FragmentExecutor on slice-eligible
    fragments: same constructor shape, same stats surface.  The worker
    hands it the splits the coordinator assigned to THIS task and the
    remote pages it already pulled through the exchange client; the
    executor shards the assigned splits over the local devices and runs
    the fragment SPMD.  Cross-host repartition and partial->final merges
    happen where they always did — in the consumer fragment, fed through
    the HTTP/spool exchange — so one kill -9'd host loses only its slice
    and FTE replays its tasks from committed spools.
    """

    mesh_trace_ctx_cls = _SliceTraceCtx

    def __init__(self, catalogs: CatalogManager, config: Optional[dict],
                 splits_by_scan, remote_pages, dynamic_filters=None):
        super().__init__(catalogs, mesh=None, config=config)
        self.splits_by_scan = splits_by_scan or {}
        self.remote_pages = remote_pages or {}
        # dynamic filters are a scan-pruning optimization; the slice path
        # skips them (semantically a no-op — the probe-side filter still
        # applies) rather than threading them through the stacked loader
        self.dynamic_filters = dynamic_filters or {}
        self.df_rows_pruned = 0
        # same exchange accounting as FragmentExecutor: bytes this task
        # pulled across the network before any operator ran
        self.exchange_bytes = sum(
            int(getattr(c.values, "nbytes", 0))
            + int(getattr(c.validity, "nbytes", 0) or 0)
            for pages in (remote_pages or {}).values()
            for p in pages
            for c in p.columns
        )
        if self.bandwidth_ledger is not None:
            self.bandwidth_ledger.exchange_bytes += self.exchange_bytes

    def _scan_splits(self, node: P.TableScan, idx: int, ndev: int):
        # ONLY the splits the coordinator assigned to this task, keyed by
        # the same preorder scan ordinal FragmentExecutor._load_walk uses
        return self.splits_by_scan.get(idx, [])

    def _load_remote_source(self, node, ndev, scans, counts, dicts):
        """Merge the fetched exchange pages once, then tile the rows
        identically onto every local device ([ndev, cap] stacks) — the
        slice ctx marks the batch replicated, so joins treat it as the
        broadcast build side without any per-device repartition."""
        pages = self.remote_pages.get(node.fragment_id, [])
        local_dicts: Dict[str, np.ndarray] = {}
        merged, total = merge_pages_to_arrays(
            pages, list(node.symbols), list(node.types_), local_dicts
        )
        for s, t in node.types_:
            if t.is_dictionary and s not in local_dicts:
                local_dicts[s] = np.array([], dtype=object)
        dicts.update(local_dicts)
        cap = self.ladder.quantize(max(total, 1))
        out: Dict[str, np.ndarray] = {}
        for sym in node.symbols:
            v, ok = merged[sym]
            stacked = np.zeros((ndev, cap), dtype=v.dtype)
            stacked[:, :total] = v[:total]
            okstack = np.zeros((ndev, cap), dtype=bool)
            okstack[:, :total] = (
                np.ones(total, dtype=bool) if ok is None else ok[:total]
            )
            out[sym] = stacked
            out[sym + "$ok"] = okstack
        scans[str(id(node))] = out
        counts[str(id(node))] = np.full(ndev, total, dtype=np.int64)


# class-attribute hook resolution: _MeshTraceCtx is defined below
# MeshExecutor, so the default binding lives here at module bottom
MeshExecutor.mesh_trace_ctx_cls = _MeshTraceCtx
