"""Hash-repartition collective: the FIXED_HASH exchange inside a mesh.

Reference parity: the reference places FIXED_HASH_DISTRIBUTION exchanges on
both inputs of a partitioned join (optimizations/AddExchanges.java:138,
SystemPartitioningHandle.java:50) and routes rows with
PagePartitioner.partitionPage (operator/output/PagePartitioner.java:134)
over the HTTP shuffle.  TPU-native redesign: inside one shard_map program
the exchange is a single `jax.lax.all_to_all` over the ICI mesh axis —
each device buckets its rows by key hash, packs them into fixed-capacity
per-destination chunks, and the collective transposes the [ndev, chunk]
send buffer so device d ends up holding exactly the rows whose keys hash
to d.  Chunk capacity is static (XLA needs fixed shapes); overflow is
detected via the executor's capacity-check ladder and retried larger, the
same recompile-on-overflow protocol the group-by uses.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops import join as join_ops

_M1 = 0xBF58476D1CE4E5B9  # python ints (see ops/int128.py const-arg note)
_M2 = 0x94D049BB133111EB


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — spreads sequential keys across buckets."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(_M1)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(_M2)
    return x ^ (x >> jnp.uint64(31))


def mix64_np(x):
    """Host (numpy) mirror of _mix64 — the skew pre-pass must land rows
    in exactly the buckets the device shuffle will."""
    import numpy as np

    m = np.uint64(0xFFFFFFFFFFFFFFFF)
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = ((x ^ (x >> np.uint64(30))) * np.uint64(_M1)) & m
        x = ((x ^ (x >> np.uint64(27))) * np.uint64(_M2)) & m
    return x ^ (x >> np.uint64(31))


def bucket_of(
    key_lanes, sel, ndev: int, force_hash: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Destination device per row: hash of the (composite) key mod ndev.

    Both join sides must call this with corresponding key lanes (and the
    same force_hash, the JOINT decision) so equal keys co-locate.
    Returns (bucket, key_ok)."""
    v, ok = join_ops.composite_key(key_lanes, sel, force_hash)
    h = _mix64(v.astype(jnp.int64))
    return (h % jnp.uint64(ndev)).astype(jnp.int32), ok


def range_buckets(
    key_lane, sort_key, sel: jnp.ndarray, ndev: int, axis: str
):
    """Destination device per row for a RANGE exchange on the leading
    sort key (SystemPartitioningHandle range-partition analog, computed
    in-mesh): sample local keys, all_gather the samples (small), pick
    ndev-1 splitters at sample quantiles, bucket = number of splitters
    strictly below the row.  Rows with EQUAL leading keys always share a
    bucket, so per-device local sorts on the FULL key list concatenate
    into a total order across devices in device order — the distributed
    sort needs no global sort and no row gather (MergeOperator's role,
    done by placement instead of merging)."""
    from ..ops import sort as sort_ops

    v, ok = key_lane
    n = sel.shape[0]
    nf = sort_key.nulls_first

    def null_bit(o):
        return jnp.logical_not(o) if not nf else o

    def strictly_above(piv_v, piv_ok):
        """row >order pivot (null ordering + direction aware)."""
        nb_row, nb_piv = null_bit(ok & sel), null_bit(piv_ok)
        if v.ndim == 2:
            from ..ops import wide_decimal as wd

            piv = jnp.broadcast_to(piv_v, v.shape)
            gt = wd.compare(v, piv, ">" if sort_key.ascending else "<")
        else:
            gt = (v > piv_v) if sort_key.ascending else (v < piv_v)
        return jnp.where(nb_row == nb_piv, gt, nb_row > nb_piv)

    # local sorted sample -> global sample -> quantile splitters.
    # Sample only the LIVE prefix (sort_perm puts unselected rows last):
    # sampling across the padded capacity would fill the splitter pool
    # with dead-row NULLs under selective filters and funnel every live
    # row to one device.
    S = 64
    perm = sort_ops.sort_perm([sort_key], {sort_key.column: key_lane}, sel)
    sv, sok = v[perm], ok[perm] & sel[perm]
    n_live = jnp.maximum(sel.sum(), 1)
    samp_idx = jnp.clip(
        jnp.arange(S) * jnp.maximum(n_live // S, 1), 0, n_live - 1
    )
    all_v = jax.lax.all_gather(sv[samp_idx], axis, axis=0, tiled=True)
    all_ok = jax.lax.all_gather(sok[samp_idx], axis, axis=0, tiled=True)
    total = all_v.shape[0]
    perm2 = sort_ops.sort_perm(
        [sort_key],
        {sort_key.column: (all_v, all_ok)},
        jnp.ones(total, bool),
    )
    gs_v, gs_ok = all_v[perm2], all_ok[perm2]
    bucket = jnp.zeros(n, dtype=jnp.int32)
    for j in range(1, ndev):
        pidx = min((j * total) // ndev, total - 1)
        bucket = bucket + strictly_above(
            gs_v[pidx], gs_ok[pidx]
        ).astype(jnp.int32)
    return bucket


def repartition(
    lanes: Dict[str, tuple],
    sel: jnp.ndarray,
    bucket: jnp.ndarray,
    keep: jnp.ndarray,
    ndev: int,
    chunk_cap: int,
    axis: str,
):
    """All-to-all exchange of the kept rows to their bucket device.

    lanes     : symbol -> (values, ok) with identical leading length n
    keep      : rows to transmit (False rows are dropped — e.g. NULL join
                keys on an inner probe side can never match)
    chunk_cap : static per-destination capacity on each source device

    Returns (new_lanes, new_sel, max_count) where the received arrays have
    length ndev*chunk_cap and max_count is the per-destination row count
    high-water mark to check against chunk_cap (retry ladder on overflow).
    """
    n = keep.shape[0]
    b = jnp.where(keep, bucket, ndev).astype(jnp.int64)
    # stable sort rows by destination; dead rows sink to the end
    _, order = jax.lax.sort(
        (b, jnp.arange(n, dtype=jnp.int64)), num_keys=1
    )
    sb = b[order]
    counts = jax.ops.segment_sum(
        jnp.where(keep, 1, 0).astype(jnp.int64),
        jnp.clip(b, 0, ndev - 1),
        num_segments=ndev,
    )
    cum_before = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int64), jnp.cumsum(counts)[:-1]]
    )
    pos = jnp.arange(n, dtype=jnp.int64) - cum_before[
        jnp.clip(sb, 0, ndev - 1)
    ]
    live = sb < ndev
    dest = jnp.where(
        live & (pos < chunk_cap), sb * chunk_cap + pos, ndev * chunk_cap
    )
    # scatter every plane into its send buffer, then exchange all planes of
    # one dtype in a single stacked all_to_all (one collective launch per
    # dtype group instead of 2 per column — ICI launch latency dominates
    # for narrow chunks)
    planes = [
        (
            "__sel__",
            jnp.zeros(ndev * chunk_cap, dtype=bool)
            .at[dest]
            .set(live, mode="drop"),
        )
    ]
    for s, (v, ok) in lanes.items():
        if v.ndim == 2:  # wide decimal: one plane per limb
            for limb in range(2):
                planes.append(
                    (
                        (s, f"v{limb}"),
                        jnp.zeros(ndev * chunk_cap, dtype=v.dtype)
                        .at[dest]
                        .set(v[order, limb], mode="drop"),
                    )
                )
        else:
            planes.append(
                (
                    (s, "v"),
                    jnp.zeros(ndev * chunk_cap, dtype=v.dtype)
                    .at[dest]
                    .set(v[order], mode="drop"),
                )
            )
        planes.append(
            (
                (s, "ok"),
                jnp.zeros(ndev * chunk_cap, dtype=bool)
                .at[dest]
                .set(ok[order] & live, mode="drop"),
            )
        )
    groups: Dict[object, list] = {}
    for key, arr in planes:
        groups.setdefault(arr.dtype, []).append((key, arr))
    received: Dict[object, jnp.ndarray] = {}
    for items in groups.values():
        stacked = jnp.stack([a for _, a in items]).reshape(
            len(items), ndev, chunk_cap
        )
        recv = jax.lax.all_to_all(
            stacked, axis, split_axis=1, concat_axis=1, tiled=False
        ).reshape(len(items), ndev * chunk_cap)
        for i, (key, _) in enumerate(items):
            received[key] = recv[i]
    new_lanes = {}
    for s, (v, _ok) in lanes.items():
        if v.ndim == 2:
            new_lanes[s] = (
                jnp.stack(
                    [received[(s, "v0")], received[(s, "v1")]], axis=-1
                ),
                received[(s, "ok")],
            )
        else:
            new_lanes[s] = (received[(s, "v")], received[(s, "ok")])
    return new_lanes, received["__sel__"], counts.max()
