"""Hash-repartition collective: the FIXED_HASH exchange inside a mesh.

Reference parity: the reference places FIXED_HASH_DISTRIBUTION exchanges on
both inputs of a partitioned join (optimizations/AddExchanges.java:138,
SystemPartitioningHandle.java:50) and routes rows with
PagePartitioner.partitionPage (operator/output/PagePartitioner.java:134)
over the HTTP shuffle.  TPU-native redesign: inside one shard_map program
the exchange is a single `jax.lax.all_to_all` over the ICI mesh axis —
each device buckets its rows by key hash, packs them into fixed-capacity
per-destination chunks, and the collective transposes the [ndev, chunk]
send buffer so device d ends up holding exactly the rows whose keys hash
to d.  Chunk capacity is static (XLA needs fixed shapes); overflow is
detected via the executor's capacity-check ladder and retried larger, the
same recompile-on-overflow protocol the group-by uses.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops import join as join_ops

_M1 = jnp.uint64(0xBF58476D1CE4E5B9)
_M2 = jnp.uint64(0x94D049BB133111EB)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — spreads sequential keys across buckets."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> jnp.uint64(30))) * _M1
    x = (x ^ (x >> jnp.uint64(27))) * _M2
    return x ^ (x >> jnp.uint64(31))


def bucket_of(key_lanes, sel, ndev: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Destination device per row: hash of the (composite) key mod ndev.

    Both join sides must call this with corresponding key lanes so equal
    keys co-locate.  Returns (bucket, key_ok)."""
    v, ok = join_ops.composite_key(key_lanes, sel)
    h = _mix64(v.astype(jnp.int64))
    return (h % jnp.uint64(ndev)).astype(jnp.int32), ok


def repartition(
    lanes: Dict[str, tuple],
    sel: jnp.ndarray,
    bucket: jnp.ndarray,
    keep: jnp.ndarray,
    ndev: int,
    chunk_cap: int,
    axis: str,
):
    """All-to-all exchange of the kept rows to their bucket device.

    lanes     : symbol -> (values, ok) with identical leading length n
    keep      : rows to transmit (False rows are dropped — e.g. NULL join
                keys on an inner probe side can never match)
    chunk_cap : static per-destination capacity on each source device

    Returns (new_lanes, new_sel, max_count) where the received arrays have
    length ndev*chunk_cap and max_count is the per-destination row count
    high-water mark to check against chunk_cap (retry ladder on overflow).
    """
    n = keep.shape[0]
    b = jnp.where(keep, bucket, ndev).astype(jnp.int64)
    # stable sort rows by destination; dead rows sink to the end
    _, order = jax.lax.sort(
        (b, jnp.arange(n, dtype=jnp.int64)), num_keys=1
    )
    sb = b[order]
    counts = jax.ops.segment_sum(
        jnp.where(keep, 1, 0).astype(jnp.int64),
        jnp.clip(b, 0, ndev - 1),
        num_segments=ndev,
    )
    cum_before = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int64), jnp.cumsum(counts)[:-1]]
    )
    pos = jnp.arange(n, dtype=jnp.int64) - cum_before[
        jnp.clip(sb, 0, ndev - 1)
    ]
    live = sb < ndev
    dest = jnp.where(
        live & (pos < chunk_cap), sb * chunk_cap + pos, ndev * chunk_cap
    )
    # scatter every plane into its send buffer, then exchange all planes of
    # one dtype in a single stacked all_to_all (one collective launch per
    # dtype group instead of 2 per column — ICI launch latency dominates
    # for narrow chunks)
    planes = [
        (
            "__sel__",
            jnp.zeros(ndev * chunk_cap, dtype=bool)
            .at[dest]
            .set(live, mode="drop"),
        )
    ]
    for s, (v, ok) in lanes.items():
        planes.append(
            (
                (s, "v"),
                jnp.zeros(ndev * chunk_cap, dtype=v.dtype)
                .at[dest]
                .set(v[order], mode="drop"),
            )
        )
        planes.append(
            (
                (s, "ok"),
                jnp.zeros(ndev * chunk_cap, dtype=bool)
                .at[dest]
                .set(ok[order] & live, mode="drop"),
            )
        )
    groups: Dict[object, list] = {}
    for key, arr in planes:
        groups.setdefault(arr.dtype, []).append((key, arr))
    received: Dict[object, jnp.ndarray] = {}
    for items in groups.values():
        stacked = jnp.stack([a for _, a in items]).reshape(
            len(items), ndev, chunk_cap
        )
        recv = jax.lax.all_to_all(
            stacked, axis, split_axis=1, concat_axis=1, tiled=False
        ).reshape(len(items), ndev * chunk_cap)
        for i, (key, _) in enumerate(items):
            received[key] = recv[i]
    new_lanes = {
        s: (received[(s, "v")], received[(s, "ok")]) for s in lanes
    }
    return new_lanes, received["__sel__"], counts.max()
