"""Session facade: SQL in, Pages out.

Reference parity: the in-process query path of testing/PlanTester.java:250 /
StandaloneQueryRunner — parse -> analyze/plan -> optimize -> execute without
a server.  The distributed path (coordinator/worker) layers on top of the
same pipeline (server/).
"""
from __future__ import annotations

from typing import Optional

from .catalog import CatalogManager, Metadata
from .connectors.tpch import TpchConnectorFactory
from .exec.local import LocalExecutor
from .page import Page
from .plan import nodes as P
from .plan.optimizer import optimize
from .sql import ast
from .sql.analyzer import Analyzer
from .sql.parser import parse


class Session:
    def __init__(
        self,
        catalog: Optional[str] = None,
        config: Optional[dict] = None,
    ):
        self.catalogs = CatalogManager()
        self.catalogs.register_factory(TpchConnectorFactory())
        self.default_catalog = catalog
        self.config = dict(config or {})
        self.metadata = Metadata(self.catalogs)
        self.executor = LocalExecutor(self.catalogs, self.config)

    def create_catalog(self, name: str, connector: str, config: dict):
        self.catalogs.create_catalog(name, connector, config)
        if self.default_catalog is None:
            self.default_catalog = name

    # ------------------------------------------------------------------
    def plan(self, sql: str, optimized: bool = True) -> P.PlanNode:
        stmt = parse(sql)
        if isinstance(stmt, ast.Explain):
            stmt = stmt.query
        analyzer = Analyzer(self.metadata, self.default_catalog)
        plan = analyzer.plan_statement(stmt)
        if optimized:
            plan = optimize(plan, self.metadata)
        return plan

    def explain(self, sql: str) -> str:
        return P.plan_to_string(self.plan(sql))

    def execute(self, sql: str) -> Page:
        stmt = parse(sql)
        if isinstance(stmt, ast.Explain):
            from .page import column_from_pylist
            from . import types as T

            text = self.explain(sql[sql.lower().index("explain") + 7 :])
            col = column_from_pylist(T.VARCHAR, text.split("\n"))
            return Page([col], len(text.split("\n")), ["Query Plan"])
        analyzer = Analyzer(self.metadata, self.default_catalog)
        plan = analyzer.plan_statement(stmt)
        plan = optimize(plan, self.metadata)
        return self.executor.execute(plan)


def tpch_session(sf: float = 0.01, **config) -> Session:
    """One-liner dev entry (TpchQueryRunner analog, SURVEY appendix A)."""
    s = Session()
    s.create_catalog("tpch", "tpch", {"tpch.scale-factor": sf})
    return s
