"""Session facade: SQL in, Pages out.

Reference parity: the in-process query path of testing/PlanTester.java:250 /
StandaloneQueryRunner — parse -> analyze/plan -> optimize -> execute, plus
the session machinery around it:
  - typed session properties + SET/SHOW SESSION (SystemSessionProperties)
  - OpenTelemetry-style spans per phase (DispatchManager querySpan)
  - query events to registered listeners (EventListenerManager)
  - per-query memory reservation against a shared pool (MemoryPool)
  - utility statements: SHOW TABLES / SHOW COLUMNS / EXPLAIN
The coordinator HTTP server (server/coordinator.py) wraps this same path.
"""
from __future__ import annotations

import time
import uuid
from typing import Optional

from . import types as T
from .catalog import CatalogManager, Metadata
from .config import SessionProperties
from .connectors.tpch import TpchConnectorFactory
from .exec.local import LocalExecutor
from .page import Page, column_from_pylist, page_from_pydict
from .plan import nodes as P
from .plan.optimizer import optimize
from .sql import ast
from .sql.analyzer import Analyzer
from .sql.parser import parse
from .utils.events import EventListenerManager
from .utils.memory import MemoryPool, estimate_batch_bytes
from .utils.tracing import TRACER


class Session:
    def __init__(
        self,
        catalog: Optional[str] = None,
        config: Optional[dict] = None,
        user: str = "user",
    ):
        self.catalogs = CatalogManager()
        self.catalogs.register_factory(TpchConnectorFactory())
        from .connectors.tpcds import TpcdsConnectorFactory

        self.catalogs.register_factory(TpcdsConnectorFactory())
        try:
            from .connectors.memory import MemoryConnectorFactory
            from .connectors.blackhole import BlackholeConnectorFactory

            self.catalogs.register_factory(MemoryConnectorFactory())
            self.catalogs.register_factory(BlackholeConnectorFactory())
        except ImportError:
            pass
        try:
            from .connectors.hive import HiveConnectorFactory

            self.catalogs.register_factory(HiveConnectorFactory())
        except ImportError:  # pyarrow not installed
            pass
        from .connectors.lakehouse import LakehouseConnectorFactory

        self.catalogs.register_factory(LakehouseConnectorFactory())
        self.default_catalog = catalog
        self.properties = SessionProperties(config)
        self.metadata = Metadata(self.catalogs)
        self.events = EventListenerManager()
        # per-node memory arbitration (memory/ subsystem): the legacy
        # session-level MemoryPool is absorbed as the manager's general
        # pool, so existing reserve/free call sites keep working
        from .memory import LocalMemoryManager

        self.memory_manager = LocalMemoryManager(
            self.properties.get("query_max_memory_bytes"),
            node_id="session",
        )
        self.memory_pool = self.memory_manager.general
        # supervised kernel-dispatch boundary (runtime/): one per node,
        # like the memory manager — device quarantine is node-local
        from .runtime import DeviceSupervisor

        self.device_supervisor = DeviceSupervisor(node_id="session")
        self.tracer = TRACER
        # PREPARE name FROM ... statements (QueryPreparer / prepared
        # statement store; the reference keeps these per client session)
        self.prepared: dict = {}
        # CREATE FUNCTION registry (LanguageFunctionManager analog)
        self.sql_functions: dict = {}
        from .security import AccessControlManager, Identity

        self.identity = Identity(user)
        self.access_control = AccessControlManager()
        # system.runtime.queries / completed_queries backing store
        # (QueryTracker history): crash-safe persisted store shared by
        # ALL sessions, bounded by bytes (not count) — mmap'd JSONL
        # segments in obs/history survive kill -9 up to the torn tail
        from .obs.history import get_store as _history_store

        self.history = _history_store(
            self.properties.get("query_history_dir") or None,
            max_bytes=int(
                self.properties.get("query_history_max_bytes")
                or (1 << 20)
            ),
        )
        # engine-wide incident journal (obs/journal.py): process-global,
        # memory-only until a directory upgrades it to the crash-safe
        # mmap'd segment store that scripts/doctor.py reads post-mortem
        from .obs import journal as _journal

        if self.properties.get("event_journal_dir"):
            _journal.configure(
                self.properties.get("event_journal_dir"),
                max_bytes=int(
                    self.properties.get("event_journal_max_bytes")
                    or (1 << 20)
                ),
            )
        # compile observatory (obs/compile_observatory.py): the process-
        # global trace/compile ledger + shape census; a directory
        # upgrades it to the same crash-safe segment store
        from .obs import compile_observatory as _compile_obs

        _census_fams = int(
            self.properties.get("compile_census_max_families")
            or _compile_obs.DEFAULT_MAX_FAMILIES
        )
        if self.properties.get("compile_observatory_dir"):
            _compile_obs.configure(
                self.properties.get("compile_observatory_dir"),
                census_max_families=_census_fams,
            )
        elif _census_fams != _compile_obs.DEFAULT_MAX_FAMILIES:
            # resize the census without re-pointing (or dropping) the
            # directory an earlier session configured
            _compile_obs.configure(
                _compile_obs.get_observatory().directory,
                census_max_families=_census_fams,
            )
        # ranked root-cause verdict of the most recent doctored query
        # (bench.py attaches it to slow configs)
        self.last_diagnosis: Optional[dict] = None
        # stats of the most recent persistent-compile-cache prewarm
        # (cold-start path; bench --serve surfaces them)
        self.last_prewarm: Optional[dict] = None
        # operator timeline of the last instrumented execution (EXPLAIN
        # ANALYZE / operator_stats=true), backing
        # system.runtime.operator_stats
        self.last_timeline: Optional[dict] = None
        # the built-in system catalog (system.runtime.* etc.)
        from .connectors.system import SystemConnectorFactory

        self.catalogs.register_factory(SystemConnectorFactory())
        self.catalogs.create_catalog("system", "system", {"session": self})
        # cross-query scan cache (warm-HBM reuse; exec/local.DeviceScanCache)
        from .exec.local import DeviceScanCache

        self._scan_cache = DeviceScanCache()
        # under memory pressure the warm-HBM scan cache is revoked
        # (spilled to nothing — it can always be re-uploaded) before any
        # query is blocked or killed
        self.memory_manager.register_revocable(
            "scan-cache", self._scan_cache.max_bytes,
            self._scan_cache.drop_all,
        )
        # unified cache subsystem (cache/): session-scoped fragment result
        # cache + process-global compiled-fragment cache, with the scan
        # cache adopted for stats (system.runtime.caches, /v1/cache)
        from .cache import CacheManager, FragmentResultCache
        from .cache import shared_compile_cache

        self.caches = CacheManager(
            FragmentResultCache(
                max_bytes=self.properties.get("result_cache_max_bytes"),
                on_event=self.events.cache_event,
            ),
            shared_compile_cache(),
            self._scan_cache,
            events=self.events,
        )
        # back-compat alias (bench/tests reach the compiled-fragment
        # cache through this name); plan cache stays keyed by SQL text
        self._jit_cache = self.caches.compile_cache
        self._plan_cache: dict = {}
        # FaultInjector instances per spec text: rules are stateful
        # (nth counters), so the same spec must reuse one injector
        self._fault_injectors: dict = {}
        self._capacity_hints: dict = {}
        # streaming fragment DAGs keyed by id(plan): re-fragmenting per
        # run would mint fresh plan objects and defeat jit-cache reuse
        self._fragment_cache: dict = {}
        # ANALYZE run registry (system.runtime.table_stats backing store):
        # (catalog, table) -> last run's shape + timings
        self.analyzed_tables: dict = {}

    def create_catalog(self, name: str, connector: str, config: dict):
        self.catalogs.create_catalog(name, connector, config)
        if self.default_catalog is None:
            self.default_catalog = name

    @property
    def query_history(self) -> list:
        """Legacy-shaped view over the persisted history store (the
        system.runtime.queries backing read): latest record per query,
        across every session sharing the store."""
        out = []
        for r in self.history.entries():
            out.append({
                "query_id": r.get("queryId"),
                "state": r.get("state"),
                "sql": r.get("sql"),
                "user": r.get("user"),
                "created": r.get("created"),
                "finished": r.get("finished"),
                "rows": r.get("rows"),
                "error": r.get("error"),
                "error_code": r.get("errorCode"),
            })
        return out

    # ------------------------------------------------------------------
    def _executor(self):
        # SET SESSION query_max_memory_bytes resizes the pool for later
        # queries (the pool object is shared; only its budget moves)
        self.memory_pool.size = self.properties.get("query_max_memory_bytes")
        inj = self._fault_injector()
        self.memory_manager.fault_injector = inj
        sup = self.device_supervisor.configure(self.properties)
        sup.fault_injector = inj
        sup.cpu_fallback_enabled = bool(
            self.properties.get("device_cpu_fallback")
        )
        exec_config = {
            "device_supervisor": sup,
            "device_cpu_fallback": self.properties.get(
                "device_cpu_fallback"
            ),
            "group_capacity": self.properties.get("group_capacity"),
            "memory_limit_bytes": self.properties.get(
                "query_max_memory_bytes"
            ),
            "spill_enabled": self.properties.get("spill_enabled"),
            "memory_pool": self.memory_pool,
            "memory_manager": self.memory_manager,
            "memory_blocked_timeout_s": self.properties.get(
                "memory_blocked_timeout_s"
            ),
            "scan_cache": (
                self._scan_cache
                if self.properties.get("scan_cache_enabled") else None
            ),
            "topn_initial_factor": self.properties.get(
                "topn_initial_factor"
            ),
            # operator_stats=true runs eager with per-node timing (jit
            # would fuse the fragment and hide the operator boundaries)
            "collect_node_stats": bool(
                self.properties.get("operator_stats")
            ),
        }
        exec_config["jit_fragments"] = bool(
            self.properties.get("jit_fragments")
        )
        exec_config["device_generation"] = bool(
            self.properties.get("device_generation")
        )
        exec_config["megakernels"] = self.properties.get("megakernels")
        exec_config["double_buffer_depth"] = self.properties.get(
            "double_buffer_depth"
        )
        exec_config["donate_pages"] = self.properties.get("donate_pages")
        exec_config["broadcast_join_threshold_rows"] = self.properties.get(
            "broadcast_join_threshold_rows"
        )
        # bucketed-batch ABI: resolve the ladder once per (spec, file)
        # and hand every executor (and its streaming tiles / mesh shards)
        # the same PaddingLadder object, so the whole session quantizes
        # onto identical rungs
        ladder_key = (
            self.properties.get("padding_ladder"),
            self.properties.get("padding_ladder_file"),
        )
        cached = getattr(self, "_ladder_cache", None)
        if not cached or cached[0] != ladder_key:
            from .exec.shapes import resolve_ladder

            cached = (ladder_key, resolve_ladder({
                "padding_ladder": ladder_key[0],
                "padding_ladder_file": ladder_key[1],
            }))
            self._ladder_cache = cached
        exec_config["padding_ladder"] = cached[1]
        cc = self.caches.compile_cache
        cache_dir = self.properties.get("compile_cache_dir")
        if cache_dir:
            # persistent tier: point jax's compilation cache at the shared
            # directory so a second process skips the XLA compile
            cc.attach_persistent(cache_dir)
            if self.properties.get("compile_prewarm"):
                # cold-start prewarm: page the persistent executables into
                # the OS cache and seed the observatory's family registry
                # from the index, so boot-time compiles classify as
                # persistent_load / first_compile — never shape_miss.
                # Idempotent per directory; records stats for bench.
                warm = cc.prewarm(cache_dir)
                if warm is not None:
                    self.last_prewarm = warm
        # session property compile_cache=false detaches the shared cache
        # (a throwaway dict keeps the executor's duck-typed surface)
        exec_config["jit_cache"] = (
            cc if self.properties.get("compile_cache") else {}
        )
        exec_config["bandwidth_ledger"] = bool(
            self.properties.get("bandwidth_ledger")
        )
        exec_config["capacity_hints"] = self._capacity_hints
        exec_config["fragment_cache"] = self._fragment_cache
        if self.properties.get("distributed"):
            from .parallel.mesh_executor import MeshExecutor, default_mesh

            n = self.properties.get("num_devices") or None
            return MeshExecutor(self.catalogs, default_mesh(n), exec_config)
        return LocalExecutor(self.catalogs, exec_config)

    # ------------------------------------------------------------------
    def plan(self, sql: str, optimized: bool = True) -> P.PlanNode:
        stmt = parse(sql)
        if isinstance(stmt, ast.Explain):
            stmt = stmt.query
        analyzer = Analyzer(self.metadata, self.default_catalog,
                            self.sql_functions)
        plan = analyzer.plan_statement(stmt)
        if optimized:
            plan = optimize(plan, self.metadata, self.properties)
        return plan

    def explain(self, sql: str) -> str:
        return P.plan_to_string(self.plan(sql))

    # ------------------------------------------------------------------
    def execute(self, sql: str, user: Optional[str] = None) -> Page:
        from .security import Identity

        identity = Identity(user) if user else self.identity
        query_id = f"q_{uuid.uuid4().hex[:12]}"
        created = self.events.query_created(query_id, sql)
        entry = {
            "query_id": query_id, "sql": sql, "state": "RUNNING",
            "user": identity.user, "created": created,
        }
        self.history.put(entry)
        try:
            with self.tracer.span("query", query_id=query_id):
                with self.tracer.span("parse"):
                    stmt = parse(sql)
                self.access_control.check_can_execute_query(identity)
                page = self._execute_statement(
                    stmt, sql, query_id, identity
                )
            self.events.query_completed(
                query_id, sql, "FINISHED", created, page.count
            )
            entry.update(
                state="FINISHED", finished=time.time(),
                rows=page.count, wall_s=time.time() - created,
            )
            # only THIS query's timeline (last_timeline is kept across
            # queries so system.runtime.operator_stats can read it)
            tl = self.last_timeline
            if tl and tl.get("queryId") == query_id:
                entry["operators"] = tl.get("operators")
            self.history.put(entry)
            self._finalize_doctor(query_id, created)
            return page
        except Exception as e:
            from .obs.doctor import classify_error

            self.events.query_completed(
                query_id, sql, "FAILED", created, error=str(e)
            )
            entry.update(
                state="FAILED", finished=time.time(),
                error=str(e), error_code=classify_error(e),
                wall_s=time.time() - created,
            )
            self.history.put(entry)
            try:
                from .obs import journal

                journal.emit(
                    journal.QUERY_FAILED, query_id=query_id,
                    severity=journal.ERROR, error=str(e)[:400],
                    errorCode=classify_error(e),
                )
            except Exception:  # noqa: BLE001 — journaling is best-effort
                pass
            self._finalize_doctor(query_id, created, error=e)
            raise
        finally:
            # batch-export completed spans on EVERY completion path —
            # success, failure, and non-Query statements alike (no-op
            # without an attached OTLP exporter)
            self.tracer.flush()

    def _finalize_doctor(self, query_id: str, created: float,
                         error=None):
        """Query-finalize doctor pass (query_doctor session property):
        correlate the incident journal, operator timeline, and kernel
        profile into a ranked verdict.  Observability must never fail
        (or re-fail) the query, so everything is best-effort."""
        try:
            if not self.properties.get("query_doctor"):
                return
            from .obs import doctor

            now = time.time()
            tl = self.last_timeline
            diag = doctor.diagnose_query(
                query_id,
                window=(created, now),
                timeline=tl if (tl or {}).get("queryId") == query_id
                else None,
                profile=getattr(self, "last_kernel_profile", None),
                error=error,
                wall_s=now - created,
            )
            doctor.record_diagnosis(diag)
            self.last_diagnosis = diag
        except Exception:  # noqa: BLE001
            pass

    def _execute_statement(self, stmt, sql: str, query_id: str,
                           identity=None) -> Page:
        if identity is None:
            identity = self.identity
        if isinstance(stmt, (
            ast.Prepare, ast.Deallocate, ast.CreateFunction,
            ast.DropFunction, ast.CreateTable, ast.DropTable, ast.Use,
            ast.SetSession, ast.CreateView, ast.DropView,
        )):
            # statements that change planning state invalidate cached
            # plans; read-only EXECUTE/SHOW/EXPLAIN keep them.  Compiled
            # fragments survive: their keys embed the plan fingerprint,
            # capacity state and per-table data versions, so entries for
            # changed schemas/data simply stop being addressable (and the
            # compile cache is process-shared — clearing it here would
            # nuke other sessions' warm programs).
            self._plan_cache.clear()
            self._capacity_hints.clear()
        if isinstance(stmt, ast.SetSession):
            self.access_control.check_can_set_session(identity, stmt.name)
            if "." in stmt.name:
                # per-catalog session property (SET SESSION catalog.name):
                # validated against the connector's declared metadata
                cat, _, prop = stmt.name.partition(".")
                conn = self.catalogs.get(cat)
                meta = conn.session_property_metadata().get(prop)
                if meta is None:
                    raise KeyError(
                        f"unknown catalog session property: {stmt.name}"
                    )
                value = (
                    meta.parse(stmt.value)
                    if isinstance(stmt.value, str) else stmt.value
                )
                conn.set_session_property(prop, value)
            else:
                self.properties.set(stmt.name, stmt.value)
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.ShowSession):
            rows = list(self.properties.show())
            # per-catalog session properties (Trino's SHOW SESSION lists
            # catalog properties alongside system ones)
            for cat in self.catalogs.names():
                conn = self.catalogs.get(cat)
                for name, meta in sorted(
                    conn.session_property_metadata().items()
                ):
                    rows.append((
                        f"{cat}.{name}",
                        str(conn.get_session_property(name)),
                        str(meta.default),
                        meta.description,
                    ))
            return page_from_pydict(
                [("name", T.VARCHAR), ("value", T.VARCHAR),
                 ("default", T.VARCHAR), ("description", T.VARCHAR)],
                {
                    "name": [r[0] for r in rows],
                    "value": [r[1] for r in rows],
                    "default": [r[2] for r in rows],
                    "description": [r[3] for r in rows],
                },
            )
        if isinstance(stmt, ast.CreateView):
            from .catalog import ViewDefinition
            from .sql.analyzer import Analyzer

            catalog, name = self.metadata.resolve_new_table(
                stmt.name, self.default_catalog
            )
            # views are named schema objects: the create-table rule
            # governs them (the reference has a dedicated
            # checkCanCreateView with the same default policy)
            self.access_control.check_can_create_table(
                identity, catalog, name
            )
            # plan the query now: validates it and captures the view's
            # declared column names/types (ViewDefinition column list)
            analyzer = Analyzer(self.metadata, self.default_catalog,
                                self.sql_functions)
            plan = analyzer.plan_statement(stmt.query)
            types = plan.source.output_types()
            cols = tuple(
                (n, str(types[s]))
                for n, s in zip(plan.names, plan.symbols)
            )
            seen = set()
            for n, _t in cols:
                if n.lower() in seen:
                    raise ValueError(f"duplicate view column name {n}")
                seen.add(n.lower())
            self.metadata.create_view(
                ViewDefinition(catalog, name, stmt.query_sql, stmt.query,
                               cols, context_catalog=self.default_catalog),
                stmt.replace,
            )
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.DropView):
            catalog, name = self.metadata.resolve_new_table(
                stmt.name, self.default_catalog
            )
            self.access_control.check_can_drop_table(
                identity, catalog, name
            )
            self.metadata.drop_view(
                stmt.name, self.default_catalog, stmt.if_exists
            )
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.ShowCreateView):
            view = self.metadata.lookup_view(stmt.name, self.default_catalog)
            if view is None:
                raise KeyError(f"view not found: {'.'.join(stmt.name)}")
            ddl = (
                f"CREATE VIEW {view.catalog}.{view.name} AS\n"
                f"{view.original_sql}"
            )
            return page_from_pydict(
                [("create_view", T.VARCHAR)], {"create_view": [ddl]}
            )
        if isinstance(stmt, ast.ShowTables):
            conn = self.catalogs.get(self.default_catalog)
            tables = sorted(
                set(conn.metadata().list_tables())
                | set(self.metadata.list_views(self.default_catalog))
            )
            return page_from_pydict([("table", T.VARCHAR)], {"table": tables})
        if isinstance(stmt, ast.ShowColumns):
            view = self.metadata.lookup_view(stmt.table, self.default_catalog)
            if view is not None:
                return page_from_pydict(
                    [("column", T.VARCHAR), ("type", T.VARCHAR)],
                    {
                        "column": [c for c, _ in view.columns],
                        "type": [t for _, t in view.columns],
                    },
                )
            _, schema = self.metadata.resolve_table(
                stmt.table, self.default_catalog
            )
            return page_from_pydict(
                [("column", T.VARCHAR), ("type", T.VARCHAR)],
                {
                    "column": [c.name for c in schema.columns],
                    "type": [str(c.type) for c in schema.columns],
                },
            )
        if isinstance(stmt, ast.CreateFunction):
            from .sql.analyzer import SqlFunction

            name = stmt.name.lower()
            if name in self.sql_functions and not stmt.replace:
                raise ValueError(f"function {name} already exists")
            T.parse_type(stmt.return_type)  # validate eagerly
            for _, pt in stmt.params:
                T.parse_type(pt)
            self.sql_functions[name] = SqlFunction(
                name, tuple((p.lower(), t) for p, t in stmt.params),
                stmt.return_type, stmt.body,
            )
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.DropFunction):
            name = stmt.name.lower()
            if name not in self.sql_functions:
                if stmt.if_exists:
                    return page_from_pydict(
                        [("result", T.BOOLEAN)], {"result": [True]}
                    )
                raise KeyError(f"function not found: {name}")
            del self.sql_functions[name]
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.ShowFunctions):
            from .expr.functions import SIGNATURES
            from .sql.analyzer import AGGREGATES

            names = sorted(
                set(SIGNATURES) | AGGREGATES | set(self.sql_functions)
            )
            kinds = [
                "sql" if n in self.sql_functions
                else "aggregate" if n in AGGREGATES
                else "scalar"
                for n in names
            ]
            return page_from_pydict(
                [("function", T.VARCHAR), ("kind", T.VARCHAR)],
                {"function": names, "kind": kinds},
            )
        if isinstance(stmt, ast.Use):
            catalog = stmt.name[0]
            self.catalogs.get(catalog)  # raises if unknown
            self.default_catalog = catalog
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.TransactionControl):
            if stmt.kind == "rollback":
                raise ValueError(
                    "ROLLBACK is not supported: statements auto-commit "
                    "(one transaction per query)"
                )
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.ShowStats):
            catalog, schema = self.metadata.resolve_table(
                stmt.table, self.default_catalog
            )
            stats = self.metadata.table_statistics(catalog, schema.name)
            names, dvs, nfs, lows, highs = [], [], [], [], []
            for c in schema.columns:
                cs = stats.columns.get(c.name)
                names.append(c.name)
                dvs.append(None if cs is None else cs.distinct_count)
                nfs.append(None if cs is None else cs.null_fraction)
                lows.append(
                    None if cs is None or cs.min_value is None
                    else str(cs.min_value)
                )
                highs.append(
                    None if cs is None or cs.max_value is None
                    else str(cs.max_value)
                )
            # summary row (the reference's NULL-column row_count row)
            names.append(None)
            dvs.append(None)
            nfs.append(None)
            lows.append(None)
            highs.append(None)
            rc = [None] * len(schema.columns) + [float(stats.row_count)]
            return page_from_pydict(
                [("column_name", T.VARCHAR),
                 ("distinct_values_count", T.DOUBLE),
                 ("nulls_fraction", T.DOUBLE),
                 ("row_count", T.DOUBLE),
                 ("low_value", T.VARCHAR),
                 ("high_value", T.VARCHAR)],
                {"column_name": names, "distinct_values_count": dvs,
                 "nulls_fraction": nfs, "row_count": rc,
                 "low_value": lows, "high_value": highs},
            )
        if isinstance(stmt, ast.Analyze):
            return self.execute_analyze(stmt, identity)
        if isinstance(stmt, ast.ShowCreateTable):
            catalog, schema = self.metadata.resolve_table(
                stmt.table, self.default_catalog
            )
            cols = ",\n   ".join(
                f"{c.name} {c.type}" for c in schema.columns
            )
            ddl = (
                f"CREATE TABLE {catalog}.{schema.name} (\n   {cols}\n)"
            )
            return page_from_pydict(
                [("create_table", T.VARCHAR)], {"create_table": [ddl]}
            )
        if isinstance(stmt, ast.ShowSchemas):
            cat = stmt.catalog or self.default_catalog
            self.catalogs.get(cat)  # raises if unknown
            # catalogs here are single-schema; expose the flattened layout
            return page_from_pydict(
                [("schema", T.VARCHAR)],
                {"schema": ["default", "information_schema"]},
            )
        if isinstance(stmt, ast.ShowCatalogs):
            return page_from_pydict(
                [("catalog", T.VARCHAR)],
                {"catalog": sorted(self.catalogs.names())},
            )
        if isinstance(stmt, ast.Prepare):
            self.prepared[stmt.name.lower()] = stmt.statement
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.Deallocate):
            if stmt.name.lower() not in self.prepared:
                raise KeyError(f"prepared statement not found: {stmt.name}")
            del self.prepared[stmt.name.lower()]
            return page_from_pydict([("result", T.BOOLEAN)], {"result": [True]})
        if isinstance(stmt, ast.ExecutePrepared):
            if stmt.name.lower() not in self.prepared:
                raise KeyError(f"prepared statement not found: {stmt.name}")
            bound = ast.substitute_parameters(
                self.prepared[stmt.name.lower()], stmt.args
            )
            nparams = ast.count_parameters(bound)
            if nparams:
                raise ValueError(
                    f"{nparams} parameter(s) left unbound; "
                    f"EXECUTE ... USING must supply all values"
                )
            return self._execute_statement(bound, sql, query_id, identity)
        if isinstance(stmt, ast.Describe):
            if stmt.name.lower() not in self.prepared:
                raise KeyError(f"prepared statement not found: {stmt.name}")
            target = self.prepared[stmt.name.lower()]
            if stmt.kind == "input":
                n = ast.count_parameters(target)
                return page_from_pydict(
                    [("position", T.BIGINT), ("type", T.VARCHAR)],
                    {"position": list(range(1, n + 1)),
                     "type": ["unknown"] * n},
                )
            # DESCRIBE OUTPUT: plan with NULL-bound parameters for typing
            n = ast.count_parameters(target)
            bound = ast.substitute_parameters(
                target, tuple(ast.Literal("null", None) for _ in range(n))
            )
            plan = self._plan_stmt(bound)
            types = plan.source.output_types()
            return page_from_pydict(
                [("column", T.VARCHAR), ("type", T.VARCHAR)],
                {
                    "column": list(plan.names),
                    "type": [str(types[s]) for s in plan.symbols],
                },
            )
        if isinstance(stmt, ast.Explain):
            if stmt.analyze:
                return self._explain_analyze(stmt.query, query_id)
            plan = self._plan_stmt(stmt.query)
            costs = None
            try:
                from .plan.cost import annotate

                costs = annotate(plan, self.metadata, self.properties)
            except Exception:
                pass
            if stmt.plan_type == "distributed":
                from .plan.fragment import fragment_plan

                parts = []
                for f in fragment_plan(plan):
                    parts.append(
                        f"Fragment {f.id} [{f.partitioning}"
                        + (f" keys={list(f.partition_keys)}"
                           if f.partition_keys else "")
                        + f" -> output {f.output_partitioning}]"
                    )
                    parts.append(
                        "\n".join(
                            "  " + line
                            for line in P.plan_to_string(f.root).split("\n")
                        )
                    )
                text = "\n".join(parts)
            else:
                text = P.plan_to_string(plan, costs=costs)
            col = column_from_pylist(T.VARCHAR, text.split("\n"))
            return Page([col], len(text.split("\n")), ["Query Plan"])
        if isinstance(stmt, ast.CreateTable):
            from .spi import ColumnSchema, TableSchema

            catalog, table = self.metadata.resolve_new_table(
                stmt.table, self.default_catalog
            )
            self.access_control.check_can_create_table(
                identity, catalog, table
            )
            if self.metadata.lookup_view(stmt.table, self.default_catalog):
                raise ValueError(
                    f"view with that name already exists: {table}"
                )
            md = self.catalogs.get(catalog).metadata()
            if stmt.if_not_exists and table in md.list_tables():
                return page_from_pydict([("rows", T.BIGINT)], {"rows": [0]})
            md.create_table(
                TableSchema(
                    table,
                    tuple(
                        ColumnSchema(c.lower(), T.parse_type(t))
                        for c, t in stmt.columns
                    ),
                )
            )
            return page_from_pydict([("rows", T.BIGINT)], {"rows": [0]})
        if isinstance(stmt, ast.DropTable):
            catalog, table = self.metadata.resolve_new_table(
                stmt.table, self.default_catalog
            )
            self.access_control.check_can_drop_table(
                identity, catalog, table
            )
            md = self.catalogs.get(catalog).metadata()
            if stmt.if_exists and table not in md.list_tables():
                return page_from_pydict([("rows", T.BIGINT)], {"rows": [0]})
            md.drop_table(table)
            self.caches.result_cache.invalidate(catalog, table)
            return page_from_pydict([("rows", T.BIGINT)], {"rows": [0]})

        if isinstance(stmt, ast.Query):
            cached = self._plan_cache.get(sql)
            if cached is None:
                cached = self._plan_stmt(stmt)
                from .cache import plan_signature

                # nondeterministic plans carry query-time folded constants
                # (now() timestamps, rand() seeds): caching the plan by SQL
                # text would replay the first execution's values forever
                if plan_signature(cached).deterministic:
                    self._plan_cache[sql] = cached
                    del_keys = list(self._plan_cache)[:-256]
                    for k in del_keys:  # bound the cache
                        self._plan_cache.pop(k, None)
            plan = cached
        else:
            # writes (INSERT/DELETE/UPDATE/MERGE/CTAS) change data: cached
            # plans are stale (compiled fragments stay — their keys embed
            # per-table data versions)
            self._plan_cache.clear()
            self._capacity_hints.clear()
            plan = self._plan_stmt(stmt)
        self._check_plan_access(plan, identity)
        rkey = None
        if isinstance(stmt, ast.Query):
            rkey, page = self.cached_result(plan)
            if page is not None:
                return page
        executor = self._executor()
        # journal/flight-recorder correlation: breadcrumbs and incident
        # events this execution emits carry the real query id, not the
        # executor's generic "query" placeholder
        executor.query_id = query_id
        with self.tracer.span("execute", query_id=query_id):
            _t0 = time.time()
            page = executor.execute(plan)
            _exec_wall = time.time() - _t0
        # input working-set size of the last query (bench + stats surface)
        self.last_scan_bytes = getattr(executor, "scan_bytes", 0)
        # per-query TPU kernel profile (compile wall / recompiles /
        # padding), surfaced via /v1/query/{id}/profile and bench output
        self.last_kernel_profile = getattr(executor, "kernel_profile", None)
        if getattr(executor, "node_stats", None):
            # operator_stats=true: node stats -> OperatorStats frames
            # (system.runtime.operator_stats + history "operators")
            from .obs import opstats as _opstats

            self.last_timeline = {
                "queryId": query_id,
                "wallS": _exec_wall,
                "operators": _opstats.frames_from_plan(
                    plan, executor.node_stats,
                    blocked_memory_s=getattr(
                        executor, "blocked_memory_s", 0.0
                    ),
                    blocked_exchange_s=getattr(
                        executor, "blocked_exchange_s", 0.0
                    ),
                ),
            }
            if getattr(executor, "mesh_tasks", None):
                # mesh execution: per-shard task rollups become stage
                # timelines, and the straggler detector sees shards the
                # way it sees worker tasks (row-skew apportioned wall)
                det = _opstats.StragglerDetector(
                    factor=float(
                        self.properties.get("straggler_dispersion_factor")
                        or 2.0
                    )
                )
                mesh_tl = _opstats.timeline_from_tasks(
                    executor.mesh_tasks, detector=det
                )
                self.last_timeline["stages"] = mesh_tl["stages"]
                if det.flags:
                    self.last_timeline["stragglers"] = det.flags
        if rkey is not None:
            self.store_result(rkey, page, plan)
        if not isinstance(stmt, ast.Query):
            self._invalidate_written_tables(plan)
        return page

    # -- fragment result cache (cache/result_cache) --------------------
    def _fault_injector(self):
        """Session FaultInjector from the fault_injection property, cached
        per spec text (rules hold nth-counters, so the same spec must keep
        reusing one injector instance)."""
        spec = self.properties.get("fault_injection")
        if not spec:
            return None
        key = spec if isinstance(spec, str) else repr(spec)
        inj = self._fault_injectors.get(key)
        if inj is None:
            from .utils.faults import FaultInjector

            inj = self._fault_injectors[key] = FaultInjector.from_spec(spec)
        return inj

    def _result_cache_key(self, plan):
        """(digest, params, table versions) result-cache key, or None when
        the plan must not be result-cached: tier disabled, nondeterministic
        plan, or any scanned connector that is not cacheable."""
        if not self.properties.get("result_cache"):
            return None
        from .cache import plan_signature

        sig = plan_signature(plan)
        if not sig.deterministic:
            return None
        versions = []
        for cat, tab in sig.tables:
            try:
                conn = self.catalogs.get(cat)
            except Exception:
                return None
            if not getattr(conn, "cacheable", True):
                return None
            versions.append((cat, tab, conn.data_version(tab)))
        return (sig.digest, sig.params, tuple(versions))

    def cached_result(self, plan):
        """Consult the result cache for a planned Query.  Returns
        (key, page): key is None when the plan is uncacheable; a non-None
        page is a hit and the query skips fragment execution entirely."""
        key = self._result_cache_key(plan)
        if key is None:
            return None, None
        rc = self.caches.result_cache
        # SET SESSION result_cache_max_bytes resizes the live budget
        rc.max_bytes = int(self.properties.get("result_cache_max_bytes"))
        page = rc.get(key, injector=self._fault_injector())
        if page is None:
            return key, None
        self.last_scan_bytes = 0  # served from cache: nothing was scanned
        self.last_kernel_profile = None  # no kernel ran either
        # relabel with THIS plan's output aliases: the digest is alias-
        # invariant, so the cached page may carry another query's names
        return key, Page(list(page.columns), page.count, list(plan.names))

    def store_result(self, key, page: Page, plan) -> None:
        if key is None:
            return
        # scanned tables ride inside the key's version component
        tables = tuple((c, t) for c, t, _v in key[2])
        self.caches.result_cache.put(key, page, tables=tables)

    def _invalidate_written_tables(self, plan) -> None:
        """Eagerly drop cached results over tables a write touched (the
        version-keyed lookups already miss; this reclaims the bytes)."""
        rc = self.caches.result_cache

        def walk(n):
            if isinstance(n, P.TableWriter):
                rc.invalidate(n.catalog, n.table)
            for s in n.sources:
                walk(s)

        walk(plan)

    def _explain_analyze(self, query, query_id: str) -> Page:
        """EXPLAIN ANALYZE: execute with per-node instrumentation and print
        the plan annotated with rows + wall time (ExplainAnalyzeOperator +
        PlanPrinter.textDistributedPlan analog; single-node executor)."""
        import time

        plan = self._plan_stmt(query)
        executor = LocalExecutor(
            self.catalogs,
            {
                "group_capacity": self.properties.get("group_capacity"),
                "collect_node_stats": True,
                "spill_enabled": False,
                "query_id": query_id,
                # EXPLAIN ANALYZE always collects the HBM bandwidth
                # ledger: its whole point is per-operator accounting
                "bandwidth_ledger": True,
            },
        )
        t0 = time.perf_counter()
        t_created = time.time()  # wall-clock window for the doctor
        page = executor.execute(plan)
        wall = time.perf_counter() - t0
        self.last_kernel_profile = getattr(executor, "kernel_profile", None)
        text = P.plan_to_string(plan, executor.node_stats)
        text += (
            f"\n\nQuery: {page.count} output rows in {wall * 1000:.2f}ms "
            f"(single node)"
        )
        # per-operator timeline (OperatorStats frames): estimated rows
        # come from the cost model so estimate-vs-observed divergence is
        # visible per operator
        from .obs import opstats as _opstats

        costs = None
        try:
            from .plan.cost import annotate

            costs = annotate(plan, self.metadata, self.properties)
        except Exception:
            pass
        frames = _opstats.frames_from_plan(
            plan, executor.node_stats, costs=costs,
            blocked_memory_s=getattr(executor, "blocked_memory_s", 0.0),
            blocked_exchange_s=getattr(
                executor, "blocked_exchange_s", 0.0
            ),
        )
        self.last_timeline = {
            "queryId": query_id, "wallS": wall, "operators": frames,
        }
        text += "\n\n" + _opstats.format_timeline(frames, wall)
        prof = self.last_kernel_profile or {}
        summary = prof.get("summary") or {}
        if summary:
            text += (
                "\n\nTPU kernel profile:"
                f"\n  kernels: {summary.get('kernels', 0)}"
                f" (compile wall {summary.get('compileWallS', 0.0) * 1000:.2f}ms,"
                f" recompiles {summary.get('recompiles', 0)},"
                f" cache hits {summary.get('cacheHits', 0)})"
                f"\n  padding: {summary.get('actualRows', 0)} rows padded to "
                f"{summary.get('paddedRows', 0)} "
                f"(ratio {summary.get('paddingRatio', 1.0):.2f}x)"
                f"\n  transfers: ~{summary.get('h2dBytes', 0)}B host->device, "
                f"~{summary.get('d2hBytes', 0)}B device->host"
            )
            for k in prof.get("kernels") or []:
                text += (
                    f"\n  kernel {k['digest']} [{k['mode']}]: "
                    f"compile {k['compileWallS'] * 1000:.2f}ms, "
                    f"executions {k['executions']}, "
                    f"compiles {k['compiles']}"
                )
            # the observatory's cause taxonomy: benign first compiles
            # vs the shape-miss retraces ROADMAP item 3 wants at zero
            # in steady state
            from .obs import compile_observatory as _co

            by_cause = summary.get("compilesByCause") or {}
            text += "\n\nCompiles:"
            if any(by_cause.values()):
                for cause in _co.CAUSES:
                    n = by_cause.get(cause, 0)
                    if n:
                        text += f"\n  {cause}: {n}"
            else:
                text += "\n  (no compiles this query)"
        bandwidth = prof.get("bandwidth") or []
        if bandwidth:
            text += (
                "\n\nHBM bandwidth ledger "
                f"(roofline {summary.get('effectiveGbps', 0.0):.2f} GB/s "
                f"effective, {summary.get('rooflinePct', 0.0):.3f}% of "
                "peak):"
            )
            for e in bandwidth:
                text += (
                    f"\n  kernel {e['kernel']} [{e['mode']}]: "
                    f"{e['gbps']:.2f} GB/s "
                    f"({e['rooflinePct']:.3f}% roofline), "
                    f"in {e['inputBytes']}B, out {e['outputBytes']}B, "
                    f"inter {e['intermediateBytes']}B over "
                    f"{e['deviceWallS'] * 1000:.2f}ms device wall"
                )
        # the doctor's causal verdict over the same evidence (EXPLAIN
        # ANALYZE is the interactive "why was this slow" surface)
        if self.properties.get("query_doctor"):
            try:
                from .obs import doctor

                diag = doctor.diagnose_query(
                    query_id,
                    window=(t_created, time.time()),
                    timeline=self.last_timeline,
                    profile=prof,
                    wall_s=wall,
                )
                doctor.record_diagnosis(diag)
                self.last_diagnosis = diag
                text += "\n\n" + doctor.format_diagnosis(diag)
            except Exception:  # noqa: BLE001 — diagnosis is best-effort
                pass
        col = column_from_pylist(T.VARCHAR, text.split("\n"))
        return Page([col], len(text.split("\n")), ["Query Plan"])

    def _check_plan_access(self, plan: P.PlanNode, identity) -> None:
        """Table-level authorization over the planned statement: SELECT on
        every scanned table, INSERT/DELETE/CREATE on write targets
        (AccessControlManager checks made by StatementAnalyzer /
        planner in the reference)."""
        ac = self.access_control

        def walk(n: P.PlanNode):
            if isinstance(n, P.TableScan):
                ac.check_can_select(
                    identity, n.catalog, n.table,
                    [c for _, c in n.assignments],
                )
            if isinstance(n, P.TableWriter):
                if n.create_schema is not None:
                    ac.check_can_create_table(identity, n.catalog, n.table)
                elif n.count_symbol is not None:  # UPDATE rewrites rows
                    ac.check_can_insert(identity, n.catalog, n.table)
                    ac.check_can_delete(identity, n.catalog, n.table)
                elif n.report_deleted:
                    ac.check_can_delete(identity, n.catalog, n.table)
                else:
                    ac.check_can_insert(identity, n.catalog, n.table)
            for s in n.sources:
                walk(s)

        walk(plan)

    def _plan_stmt(self, stmt) -> P.PlanNode:
        with self.tracer.span("analyze_plan"):
            analyzer = Analyzer(self.metadata, self.default_catalog,
                            self.sql_functions)
            plan = analyzer.plan_statement(stmt)
        with self.tracer.span("optimize"):
            plan = optimize(plan, self.metadata, self.properties)
        return plan

    # -- ANALYZE (stats/ collection) -----------------------------------
    def execute_analyze(self, stmt, identity=None, execute_plan=None):
        """ANALYZE <table> [(cols)]: collect, store, register.  The
        coordinator passes `execute_plan` to run the synthesized
        aggregations through the distributed fragment scheduler instead
        of the in-process executor."""
        if identity is None:
            identity = self.identity
        catalog, schema = self.metadata.resolve_table(
            stmt.table, self.default_catalog
        )
        self.access_control.check_can_select(
            identity, catalog, schema.name,
            list(stmt.columns) or [c.name for c in schema.columns],
        )
        started = time.time()
        stats = self.collect_statistics(
            catalog, schema, stmt.columns, execute_plan=execute_plan
        )
        version = self.metadata.store_table_statistics(
            catalog, schema.name, stats
        )
        self.record_analyze(
            catalog, schema.name,
            stmt.columns or tuple(c.name for c in schema.columns),
            stats, version, started,
        )
        return page_from_pydict(
            [("rows", T.BIGINT)], {"rows": [int(stats.row_count)]}
        )

    def collect_statistics(self, catalog: str, schema, columns=(),
                           execute_plan=None):
        """Run the synthesized ANALYZE aggregations and assemble a
        TableStatistics.  The collection is ordinary SQL through the
        normal planner (QueryPlanner.planStatisticsAggregation analog),
        so under distributed=true the HLL/KMV partial-final merge rides
        the mesh like any aggregation; `execute_plan` lets the
        coordinator dispatch the same plans through its scheduler."""
        from .stats import analyze_queries, assemble, column_tasks

        buckets = max(1, int(self.properties.get("analyze_histogram_buckets")))
        tasks = column_tasks(schema, columns)
        qualified = f"{catalog}.default.{schema.name}"
        if execute_plan is None:
            executor = self._executor()
            execute_plan = executor.execute
        chunk_results = []
        with self.tracer.span("analyze_collect", table=qualified):
            for csql, chunk in analyze_queries(qualified, tasks, buckets):
                page = execute_plan(self._plan_stmt(parse(csql)))
                row = [
                    c.to_python(page.count)[0] if page.count else None
                    for c in page.columns
                ]
                chunk_results.append((chunk, row))
        return assemble(chunk_results, buckets)

    def record_analyze(self, catalog: str, table: str, columns,
                       stats, data_version: int, started: float) -> None:
        """Registry entry + invalidation after statistics storage: cached
        plans were costed without these stats."""
        from .utils.metrics import counter

        self.analyzed_tables[(catalog, table)] = {
            "catalog": catalog,
            "table": table,
            "columns": tuple(columns),
            "row_count": float(stats.row_count),
            "data_version": int(data_version),
            "analyzed_at": started,
            "duration_s": max(0.0, time.time() - started),
        }
        self._plan_cache.clear()
        self._capacity_hints.clear()
        counter("trino_tpu_stats_analyze_total").inc()


def tpch_session(sf: float = 0.01, **config) -> Session:
    """One-liner dev entry (TpchQueryRunner analog, SURVEY appendix A)."""
    s = Session(config=config)
    s.create_catalog("tpch", "tpch", {"tpch.scale-factor": sf})
    return s


def tpcds_session(sf: float = 0.01, **config) -> Session:
    s = Session(config=config)
    s.create_catalog("tpcds", "tpcds", {"tpcds.scale-factor": sf})
    return s
