"""Streaming (bounded-working-set) local execution.

Reference parity: the reference's ENTIRE worker runtime streams —
operator/Driver.java:372 moves bounded Pages through the operator chain,
ScanFilterAndProjectOperator.java:190 pulls split by split, and
project/PageProcessor.java:53 caps batches at 8192 rows, so one node can
scan a table far bigger than memory.

TPU-first redesign: XLA wants large static-shape programs, not 8k-row
batches — so the streaming unit here is an HBM-sized TILE of splits, and
the carried state is the same PARTIAL page state the distributed path
ships between workers.  The optimized plan is cut by the regular
Fragmenter (plan/fragment.py); each SOURCE fragment's splits are then
executed tile-by-tile through a FragmentExecutor (one compiled XLA
program, reused across tiles because every tile has the same padded
shape), and its partial output pages accumulate host-side.  Downstream
fragments consume the gathered partials exactly as a remote worker
would.  In effect: local streaming IS distributed execution with one
worker and host RAM as the exchange buffer — one mechanism, both
scales (and any plan the cluster can run, one chip can now run).

Build-side/remote input pages are uploaded to the device once per
streaming run (a shared DeviceScanCache entry keyed by fragment id), so
tiles re-dispatch against resident build tables instead of re-uploading
them (the LazyBlock-stays-resident analog for a tunnel-attached TPU).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

from ..page import Page
from ..plan import nodes as P
from ..plan.fragment import fragment_plan

# a tile's scan working set is bounded to limit/SAFETY so scan arrays +
# kernel temporaries + partial state fit together (same factor the spill
# framework uses)
SAFETY_FACTOR = 3


def _scan_row_bytes(node: P.TableScan) -> int:
    total = 0
    for _sym, _col in node.assignments:
        t = dict(node.types)[_sym]
        width = 8
        try:
            width = t.np_dtype.itemsize
        except NotImplementedError:
            pass
        if getattr(t, "wide", False):
            width = 16
        total += width + 1  # validity byte
    return max(total, 1)


def _est_scan_bytes(executor, catalog: str, table: str, node) -> float:
    conn = executor.catalogs.get(catalog)
    try:
        stats = conn.metadata().get_table_statistics(table)
    except Exception:  # noqa: BLE001 — unknown stats: assume small
        return 0.0
    return float(stats.row_count) * _scan_row_bytes(node)


def _find_scan_nodes(root: P.PlanNode) -> List[P.TableScan]:
    out: List[P.TableScan] = []

    def walk(n: P.PlanNode):
        if isinstance(n, P.TableScan):
            out.append(n)
        for s in n.sources:
            walk(s)

    walk(root)
    return out


def estimate_plan_scan_bytes(executor, plan: P.PlanNode) -> float:
    return sum(
        _est_scan_bytes(executor, sc.catalog, sc.table, sc)
        for sc in _find_scan_nodes(plan)
    )


def _wide_agg_count(plan: P.PlanNode) -> int:
    """Aggregates whose accumulation runs 128-bit chunked math at input
    width (decimal sums/avgs): each adds full-width u32 chunk-lane
    temporaries to the compiled program's HBM peak."""
    n = 0

    def walk(node: P.PlanNode):
        nonlocal n
        if isinstance(node, P.Aggregate):
            for a in node.aggs:
                try:
                    if a.to_spec()._wide_sum:
                        n += 1
                except Exception:  # noqa: BLE001
                    pass
        for s in node.sources:
            walk(s)

    walk(plan)
    return n


# u64 lanes the generator program keeps live per row on top of its
# output lanes: the row-index lane, the splitmix64 hash state, one value
# lane (reused across columns), and lineitem's cumsum/searchsorted slot
# machinery
DEVGEN_TEMP_LANES = 4


def _devgen_temp_bytes(executor, plan: P.PlanNode) -> float:
    """HBM temporaries of on-device scan generation.  These were the
    BENCH_r05 blind spot: estimate_program_bytes covered scan lanes and
    wide-agg chunk temporaries, but a device-generated scan ALSO runs a
    splitmix64 hash chain over the full padded row range, and its u64
    intermediates sat outside the reserve-before-dispatch accounting —
    so the first q6_sf100 generator compile exceeded the reservation and
    killed the worker process."""
    if not executor.config.get("device_generation", True):
        return 0.0
    total = 0.0
    for sc in _find_scan_nodes(plan):
        conn = executor.catalogs.get(sc.catalog)
        if getattr(conn, "device_generation", None) is None:
            continue
        try:
            stats = conn.metadata().get_table_statistics(sc.table)
        except Exception:  # noqa: BLE001 — unknown stats: assume small
            continue
        total += float(stats.row_count) * 8.0 * DEVGEN_TEMP_LANES
    return total


def estimate_program_bytes(executor, plan: P.PlanNode) -> float:
    """Estimated HBM peak of the MONOLITHIC compiled program: scan lanes
    plus wide-decimal accumulation temporaries plus on-device generator
    temporaries.  Calibrated against the
    one measured data point — Q1 SF20 (scan est 7.1 GB, 7 wide aggs)
    compiled to a 20.6 GB buffer assignment (r04's q1_sf20 hard error:
    XLA's own message, reproduced 2026-07-31) — so the gate streams
    BEFORE submitting a compile whose OOM would crash the TPU worker
    process and poison the tunnel for the fallback."""
    scan = estimate_plan_scan_bytes(executor, plan)
    return (
        scan * (1.0 + 0.28 * _wide_agg_count(plan))
        + _devgen_temp_bytes(executor, plan)
    )


# additive per-dispatch counters a tile executor accumulates that must
# surface in the PARENT executor's kernel profile (the session and bench
# read only the outer profile; tile FragmentExecutors are discarded)
_TILE_COUNTERS = (
    "preuploads", "preupload_bytes", "donated_dispatches",
    "donated_bytes", "fusedAggregates", "fusedTerms", "fusionRejects",
)


def _merge_tile_counters(executor, fe) -> None:
    prof = fe.kernel_profile
    for k in _TILE_COUNTERS:
        v = prof.get(k)
        if v:
            executor.kernel_profile[k] = (
                executor.kernel_profile.get(k, 0) + v
            )
    if prof.get("lastFusionReject"):
        executor.kernel_profile["lastFusionReject"] = (
            prof["lastFusionReject"]
        )


def plan_streaming(executor, plan: P.Output, memory_limit: int,
                   force: bool = False):
    """Decide whether to stream: the estimated total scan working set
    exceeds the memory limit and the plan fragments cleanly.  Returns
    the fragment list or None.  `force` skips the scan-bytes gate — the
    compile-OOM fallback path already KNOWS the monolithic program does
    not fit (XLA's buffer assignment said so), whatever the scans sum
    to."""
    # gate on the COMPILED program's peak, not just the scan working set:
    # wide-decimal accumulators inflate XLA's buffer assignment well past
    # the scan bytes (the Q1 SF20 calibration point), and the whole point
    # of the gate is streaming before a compile-OOM can kill the worker
    if not force and max(
        estimate_plan_scan_bytes(executor, plan),
        estimate_program_bytes(executor, plan),
    ) <= memory_limit:
        return None
    # cache the fragment DAG per plan object: fragment roots key the jit
    # cache by identity, so re-fragmenting would recompile every tile
    # program on every run (and leak the old executables).  Entries are
    # stored only AFTER the tileability checks pass ("refused" plans are
    # cached as False), so a cache hit is always a vetted DAG.
    fcache = executor.config.get("fragment_cache")
    fkey = (id(plan), int(memory_limit))  # vetting depends on the budget
    cached = fcache.get(fkey) if fcache is not None else None
    # entries carry the plan object itself: the reference pins id(plan)
    # against recycling (the fragment DAG does not reference the plan)
    if cached is not None and cached[0] is plan:
        return None if cached[1] is False else cached[1]

    def _remember(value):
        if fcache is not None:
            fcache[fkey] = (plan, value)
            for k in list(fcache)[:-256]:
                fcache.pop(k, None)
        return None if value is False else value

    try:
        frags = fragment_plan(plan)
    except NotImplementedError:
        return _remember(False)
    if len(frags) < 2:
        return _remember(False)  # nothing to tile (plain scan output)
    # every oversized scan must sit in a tileable SOURCE fragment;
    # oversized build/gather-side scans are the (partitioned) join-spill
    # framework's job, not ours
    budget = max(memory_limit // SAFETY_FACTOR, 1)
    by_id = {f.id: f for f in frags}

    def _reduces(n: P.PlanNode) -> bool:
        if isinstance(
            n, (P.Aggregate, P.TopN, P.Distinct, P.Limit)
        ):
            return True
        return any(_reduces(s) for s in n.sources)

    for f in frags:
        oversized = any(
            _est_scan_bytes(
                executor, cat, tab, _find_scan_nodes(f.root)[idx]
            ) > budget
            for idx, (cat, tab, _cons) in f.scan_tables.items()
        )
        if not oversized:
            continue
        if f.partitioning != "source":
            return _remember(False)
        # an oversized fragment gathered straight into its consumer must
        # REDUCE (partial agg/topN/limit), or the tile outputs simply
        # re-materialize the oversized input downstream (pure sorts
        # belong to the spilled-sort merge).  BROADCAST/HASH outputs are
        # join inputs the consumer needs resident regardless — tiling
        # still bounds the SCAN working set, so those may pass.
        if f.output_partitioning == "single" and not _reduces(f.root):
            return _remember(False)
    if 0 not in by_id:
        return _remember(False)
    return _remember(frags)


def execute_streaming(executor, plan: P.Output, frags, memory_limit: int) -> Page:
    """Run the fragment DAG locally, tiling SOURCE fragments' splits."""
    from .fragment_exec import FragmentExecutor
    from .local import DeviceScanCache

    budget = max(memory_limit // SAFETY_FACTOR, 1)
    by_id = {f.id: f for f in frags}
    pages_by_fragment: Dict[int, List[Page]] = {}
    # device residency for build/remote inputs across tiles, scoped to
    # this streaming run (tiles must not thrash the session scan cache).
    # Cross-run isolation comes from the FRESH cache object per run; the
    # remote cache keys themselves are stable so the jit-cache key (which
    # embeds scan keys) stays warm across repeat executions.
    run_cache = DeviceScanCache()

    def tile_config() -> dict:
        cfg = dict(executor.config)
        # tiles quantize on the parent's resolved ladder object — not a
        # re-parse of the spec — so a census-tuned ladder file read at
        # session start governs every tile of the run identically
        cfg["padding_ladder"] = executor.ladder
        # the per-query pool would double-count across tiles, and
        # spill-in-tile would recurse — but the LIMIT stays enforced:
        # when split granularity cannot realize the planned tile count
        # (e.g. a hive table stored as one giant row group), the tile's
        # own _account_memory raises loudly instead of silently running
        # unbounded.
        cfg.pop("memory_pool", None)
        cfg.pop("memory_manager", None)
        cfg["spill_enabled"] = False
        cfg["scan_cache"] = None
        return cfg

    done = set()

    def run_fragment(fid: int):
        if fid in done:
            return
        f = by_id[fid]
        for src in f.source_fragments:
            run_fragment(src)
        remote = {
            src: pages_by_fragment[src] for src in f.source_fragments
        }
        scan_nodes = _find_scan_nodes(f.root)
        if f.partitioning == "source":
            (idx, (cat, tab, cons)) = next(iter(f.scan_tables.items()))
            conn = executor.catalogs.get(cat)
            est = _est_scan_bytes(executor, cat, tab, scan_nodes[idx])
            ntiles = max(1, math.ceil(est / budget))
            splits = conn.split_manager().get_splits(tab, ntiles, cons)
            per = max(1, math.ceil(len(splits) / ntiles))
            # one padded shape for (almost) all tiles -> one compiled
            # program; generous slack so row-count jitter stays inside
            try:
                rows = conn.metadata().get_table_statistics(tab).row_count
            except Exception:  # noqa: BLE001
                rows = 0
            est_tile_rows = int(rows * per / max(len(splits), 1) * 1.3)
            # quantize the shared tile shape onto the executor's ladder:
            # tiles from different table sizes / split factors land on
            # the same rung and reuse one compiled program engine-wide
            est_tile_rows = executor.ladder.quantize(max(est_tile_rows, 128))
            tile_starts = list(range(0, len(splits), per))

            def make_loaded(i: int) -> FragmentExecutor:
                cfg = tile_config()
                if est_tile_rows:
                    cfg["scan_cap_override"] = est_tile_rows
                fe = FragmentExecutor(
                    executor.catalogs, cfg,
                    {idx: splits[i: i + per]}, remote,
                )
                fe._streaming_cache = run_cache
                fe.preload(f.root)
                # start the next tile's H2D copies on this (prefetch)
                # thread: jnp.asarray enqueues the transfer async, so it
                # overlaps the CURRENT tile's kernel instead of
                # serializing in front of the next dispatch
                fe.preupload(f.root)
                return fe

            # double-buffered tile pipeline: while tile i computes on the
            # device (the execute thread blocks in device_get), tile i+1's
            # host arrays generate/decode AND upload on the prefetch
            # thread(s) — the steady state is bound by
            # max(host, H2D, device), not their sum (SURVEY §7 hard part
            # 6).  `double_buffer_depth` is how many tiles may be staged
            # ahead of the executing one (each staged tile holds its scan
            # working set in HBM, so depth multiplies tile residency).
            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            depth = max(
                1, int(executor.config.get("double_buffer_depth", 1) or 1)
            )
            out: List[Page] = []
            with ThreadPoolExecutor(max_workers=depth) as prefetch:
                pending = deque(
                    prefetch.submit(make_loaded, i)
                    for i in tile_starts[:depth]
                )
                nexti = depth
                while pending:
                    fe = pending.popleft().result()
                    if nexti < len(tile_starts):
                        pending.append(
                            prefetch.submit(
                                make_loaded, tile_starts[nexti]
                            )
                        )
                        nexti += 1
                    out.append(fe.execute(f.root))
                    _merge_tile_counters(executor, fe)
            pages_by_fragment[fid] = out
        else:
            splits_by_scan = {}
            for idx, (cat, tab, cons) in f.scan_tables.items():
                conn = executor.catalogs.get(cat)
                splits_by_scan[idx] = conn.split_manager().get_splits(
                    tab, 1, cons
                )
            fe = FragmentExecutor(
                executor.catalogs, tile_config(), splits_by_scan, remote
            )
            fe._streaming_cache = run_cache
            pages_by_fragment[fid] = [fe.execute(f.root)]
            _merge_tile_counters(executor, fe)
        done.add(fid)

    run_fragment(0)
    (result,) = pages_by_fragment[0]
    return result
