"""Out-of-core (spilled) aggregation: split-batched partials merged on host.

Reference parity: spiller/ (FileSingleStreamSpiller feeding
SpillableHashAggregationBuilder -> MergingHashAggregationBuilder) triggered
by memory/MemoryRevokingScheduler.java:47 when revocable memory exceeds the
pool.  The reference serializes agg-builder state to local disk and merges
sorted runs; the TPU-native analog keeps HBM as the scarce tier and *host
RAM as the spill target* (SURVEY §7 step 7): scan splits are processed in
batches sized to the memory limit, each batch's PARTIAL aggregation output
(small accumulator pages) is retained on the host, and one final
FINAL/INTERMEDIATE merge runs over the concatenated partial pages.

The same partial/final kernels used by the distributed exchange do the
merging, so spill shares its correctness surface with multi-node execution.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..plan import nodes as P

SPILL_SOURCE_ID = -1  # RemoteSource id for in-process spilled partials
SAFETY_FACTOR = 4  # batch working-set headroom under the limit


def find_spillable_aggregate(
    plan: P.Output,
) -> Optional[Tuple[P.Aggregate, P.TableScan]]:
    """Match a plan whose (unique) Aggregate sits over a pure scan chain
    (Filter/Project only down to one TableScan) and is partializable.
    Anything above the Aggregate is fine — it runs after the merge."""
    found: List[P.Aggregate] = []

    def find_agg(n: P.PlanNode):
        if isinstance(n, P.Aggregate):
            found.append(n)
            return
        for s in n.sources:
            find_agg(s)

    find_agg(plan)
    if len(found) != 1:
        return None
    agg = found[0]
    if agg.step not in ("single", "partial"):
        return None
    if not all(a.partializable for a in agg.aggs):
        return None
    node = agg.source
    while isinstance(node, (P.Filter, P.Project)):
        node = node.source
    if not isinstance(node, P.TableScan):
        return None
    # the aggregate's scan must be the plan's only scan: the rewritten plan
    # replaces the whole scan chain, so remaining scans would lose their
    # split assignment
    nscans = [0]

    def count_scans(n: P.PlanNode):
        if isinstance(n, P.TableScan):
            nscans[0] += 1
        for s in n.sources:
            count_scans(s)

    count_scans(plan)
    if nscans[0] != 1:
        return None
    return agg, node


def scan_row_bytes(scan: P.TableScan) -> int:
    return sum(t.np_dtype.itemsize + 1 for _, t in scan.types)


def _replace_aggregate(
    node: P.PlanNode, agg: P.Aggregate, replacement: P.PlanNode
) -> P.PlanNode:
    if node is agg:
        return replacement
    new_sources = tuple(
        _replace_aggregate(s, agg, replacement) for s in node.sources
    )
    if all(a is b for a, b in zip(new_sources, node.sources)):
        return node
    import dataclasses

    if isinstance(node, P.SetOperation):
        return dataclasses.replace(node, inputs=new_sources)
    # other plan nodes hold their sources as individual PlanNode fields in
    # declaration order matching .sources
    updates = {}
    src_iter = iter(new_sources)
    for f in dataclasses.fields(node):
        if isinstance(getattr(node, f.name), P.PlanNode):
            updates[f.name] = next(src_iter)
    return dataclasses.replace(node, **updates)


def execute_spilled_aggregation(
    executor,  # LocalExecutor or FragmentExecutor (late import cycle)
    plan: P.Output,
    agg: P.Aggregate,
    scan: P.TableScan,
    splits: List,
    batch_size: int,
):
    """Run the scan->partial-agg pipeline per split batch, keep partial
    pages on host, then run the rewritten plan (Aggregate replaced by a
    merge over the spilled partials)."""
    from .fragment_exec import FragmentExecutor

    partial = P.Aggregate(agg.source, agg.keys, agg.aggs, "partial")
    syms = tuple(partial.output_symbols())
    partial_plan = P.Output(partial, syms, syms)

    # the plan's only scan is preorder index 0 in both the original fragment
    # and the partial subplan, so collected dynamic filters carry over
    dyn_filters = getattr(executor, "dynamic_filters", None)
    orig_remote = dict(getattr(executor, "remote_pages", {}) or {})

    partial_pages = []
    rows_pruned = 0
    scan_bytes = 0
    batch_config = dict(executor.config)
    batch_config.pop("memory_limit_bytes", None)  # batches are pre-sized
    for start in range(0, max(len(splits), 1), batch_size):
        batch = splits[start : start + batch_size]
        sub = FragmentExecutor(
            executor.catalogs, batch_config, {0: batch}, orig_remote,
            dyn_filters,
        )
        partial_pages.append(sub.execute(partial_plan))
        rows_pruned += sub.df_rows_pruned
        scan_bytes += sub.scan_bytes

    merged_step = "final" if agg.step == "single" else "intermediate"
    rs = P.RemoteSource(
        SPILL_SOURCE_ID, syms, tuple(partial.output_types().items())
    )
    merged = P.Aggregate(rs, agg.keys, agg.aggs, merged_step)
    rewritten = _replace_aggregate(plan, agg, merged)

    # the rewritten plan has no TableScan (single-scan precondition) but may
    # still hold RemoteSources above the aggregate (e.g. a broadcast build
    # side of a join over the agg) — keep the fragment's original pages
    merged_remote = dict(orig_remote)
    merged_remote[SPILL_SOURCE_ID] = partial_pages
    final_ex = FragmentExecutor(
        executor.catalogs, batch_config, {}, merged_remote
    )
    page = final_ex.execute(rewritten)
    # surface batch stats on the outer executor (task info reporting)
    executor.df_rows_pruned = rows_pruned
    executor.scan_bytes = scan_bytes
    return page


def plan_spill(
    executor,
    plan: P.Output,
    memory_limit: int,
) -> Optional[Tuple[P.Aggregate, P.TableScan, List, int]]:
    """Decide whether to spill: returns (agg, scan, splits, batch_size) when
    the estimated scan working set exceeds the limit (the same threshold
    _account_memory enforces) and the plan shape allows out-of-core
    aggregation.  Batches are sized to limit/SAFETY_FACTOR so each batch
    plus kernel temporaries stays under the limit."""
    match = find_spillable_aggregate(plan)
    if match is None:
        return None
    agg, scan = match
    conn = executor.catalogs.get(scan.catalog)
    est_table = conn.metadata().get_table_statistics(
        scan.table
    ).row_count * scan_row_bytes(scan)
    batch_budget = max(memory_limit // SAFETY_FACTOR, 1)

    splits_map: Dict[int, List] = getattr(executor, "splits_by_scan", None)
    if splits_map is not None:
        # fragment executor: this task's assigned splits of the (single,
        # preorder-index-0) scan
        splits = splits_map.get(0, [])
        if not splits:
            return None
        est = est_table * len(splits) / max(splits[0].total, 1)
        if est <= memory_limit:
            return None
        per_split = est / len(splits)
        batch = max(1, int(batch_budget / max(per_split, 1)))
        if batch >= len(splits):
            return None
        return agg, scan, splits, batch
    if est_table <= memory_limit:
        return None
    nbatches = math.ceil(est_table / batch_budget)
    splits = conn.split_manager().get_splits(
        scan.table, nbatches, scan.constraint
    )
    if len(splits) <= 1:
        return None
    return agg, scan, splits, max(1, len(splits) // nbatches)


# ---------------------------------------------------------------------
# Join / sort / window out-of-core execution (round 2).
#
# Reference parity: operator/join/HashBuilderOperator.java:162-182
# (SPILLING_INPUT state machine over spiller/GenericPartitioningSpiller),
# OrderByOperator's spillable PagesIndex, and window partition spill —
# all triggered by execution/MemoryRevokingScheduler.java:47.
#
# TPU-native redesign: host RAM is the spill tier (HBM<->host DMA is the
# new "disk").  Each input side is evaluated split-batch-wise on device
# and its result pages retained on host; joins then co-partition both
# sides by key hash (the partitioned lookup join) and run one device join
# per partition; sorts merge device-sorted runs host-side; windows run
# per hash-partition-batch of their PARTITION BY keys.

JOIN_LEFT_ID = -2
JOIN_RIGHT_ID = -3
JOIN_OUT_ID = -4
SORT_RUNS_ID = -5
WINDOW_SRC_ID = -6
DISTINCT_SRC_ID = -7


def _is_scan_chain(node: P.PlanNode) -> Optional[P.TableScan]:
    while isinstance(node, (P.Filter, P.Project)):
        node = node.source
    return node if isinstance(node, P.TableScan) else None


def _single(plan: P.Output, node_type) -> Optional[P.PlanNode]:
    found: List[P.PlanNode] = []

    def walk(n: P.PlanNode):
        if isinstance(n, node_type):
            found.append(n)
        for s in n.sources:
            walk(s)

    walk(plan)
    return found[0] if len(found) == 1 else None


def _est_side(executor, scan: P.TableScan) -> float:
    conn = executor.catalogs.get(scan.catalog)
    stats = conn.metadata().get_table_statistics(scan.table)
    return stats.row_count * scan_row_bytes(scan)


def _spill_ctx(executor):
    """Config + carried-over state for spill sub-executors: the spill
    framework owns memory, and the outer fragment's exchange pages /
    dynamic filters must stay visible (a RemoteSource above the spilled
    node would otherwise silently read zero rows)."""
    cfg = dict(executor.config)
    cfg.pop("memory_limit_bytes", None)
    cfg.pop("memory_pool", None)
    cfg.pop("memory_manager", None)
    orig_remote = dict(getattr(executor, "remote_pages", {}) or {})
    dyn = getattr(executor, "dynamic_filters", None)
    return cfg, orig_remote, dyn


def _side_pages(executor, side: P.PlanNode, scan: P.TableScan,
                memory_limit: int):
    """Evaluate one input side per split batch on device; pages stay on
    host (the spill tier)."""
    from .fragment_exec import FragmentExecutor

    syms = tuple(side.output_symbols())
    side_plan = P.Output(side, syms, syms)
    conn = executor.catalogs.get(scan.catalog)
    est = _est_side(executor, scan)
    batch_budget = max(memory_limit // SAFETY_FACTOR, 1)
    nbatches = max(1, math.ceil(est / batch_budget))
    splits = conn.split_manager().get_splits(
        scan.table, nbatches, scan.constraint
    )
    batch = max(1, len(splits) // nbatches)
    cfg, orig_remote, dyn = _spill_ctx(executor)
    pages = []
    for start in range(0, len(splits), batch):
        sub = FragmentExecutor(
            executor.catalogs, cfg, {0: splits[start : start + batch]},
            orig_remote, dyn,
        )
        pages.append(sub.execute(side_plan))
    return pages, syms, tuple(side.output_types().items())


def plan_join_spill(executor, plan: P.Output, memory_limit: int):
    """Out-of-core partitioned join: both inputs are scan chains whose
    combined estimate exceeds the memory limit."""
    join = _single(plan, P.Join)
    if join is None or join.kind not in ("inner", "left") or not join.criteria:
        return None
    lscan = _is_scan_chain(join.left)
    rscan = _is_scan_chain(join.right)
    if lscan is None or rscan is None:
        return None
    nscans = [0]

    def _count(n):
        if isinstance(n, P.TableScan):
            nscans[0] += 1
        for s in n.sources:
            _count(s)

    _count(plan)
    if nscans[0] != 2:
        return None
    est = _est_side(executor, lscan) + _est_side(executor, rscan)
    if est <= memory_limit:
        return None
    npart = max(2, math.ceil(est * 2 / memory_limit))
    return (join, lscan, rscan, npart)


def execute_spilled_join(executor, plan, join, lscan, rscan, npart):
    """Phase 1: evaluate + host-partition both sides by key hash
    (GenericPartitioningSpiller).  Phase 2: one device join per partition
    (partition-restore of HashBuilderOperator).  Phase 3: the plan above
    the join runs over the spilled join output."""
    import dataclasses

    from ..exec.partitioner import partition_page
    from .fragment_exec import FragmentExecutor

    limit = int(executor.config.get("memory_limit_bytes"))
    lkeys = [l for l, _ in join.criteria]
    rkeys = [r for _, r in join.criteria]

    lparts: List[List] = [[] for _ in range(npart)]
    rparts: List[List] = [[] for _ in range(npart)]
    for side, scan, keys, parts in (
        (join.left, lscan, lkeys, lparts),
        (join.right, rscan, rkeys, rparts),
    ):
        pages, _, _ = _side_pages(executor, side, scan, limit)
        for page in pages:
            for p, sub in enumerate(partition_page(page, keys, npart)):
                if sub.count:
                    parts[p].append(sub)

    lsyms = tuple(join.left.output_symbols())
    rsyms = tuple(join.right.output_symbols())
    ltypes = tuple(join.left.output_types().items())
    rtypes = tuple(join.right.output_types().items())
    jsyms = tuple(join.output_symbols())
    jtypes = tuple(join.output_types().items())
    part_join = dataclasses.replace(
        join,
        left=P.RemoteSource(JOIN_LEFT_ID, lsyms, ltypes),
        right=P.RemoteSource(JOIN_RIGHT_ID, rsyms, rtypes),
    )
    jplan = P.Output(part_join, jsyms, jsyms)
    cfg, orig_remote, dyn = _spill_ctx(executor)
    join_pages = []
    for p in range(npart):
        if not lparts[p]:
            continue
        if not rparts[p] and join.kind != "left":
            continue
        remote = dict(orig_remote)
        remote[JOIN_LEFT_ID] = lparts[p]
        remote[JOIN_RIGHT_ID] = rparts[p]
        sub = FragmentExecutor(executor.catalogs, cfg, {}, remote, dyn)
        page = sub.execute(jplan)
        if page.count:
            join_pages.append(page)

    rewritten = _replace_aggregate(
        plan, join, P.RemoteSource(JOIN_OUT_ID, jsyms, jtypes)
    )
    merged_remote = dict(orig_remote)
    merged_remote[JOIN_OUT_ID] = join_pages
    final = FragmentExecutor(
        executor.catalogs, cfg, {}, merged_remote, dyn
    )
    return final.execute(rewritten)


def plan_sort_spill(executor, plan: P.Output, memory_limit: int):
    sort = _single(plan, P.Sort)
    if sort is None:
        return None
    scan = _is_scan_chain(sort.source)
    if scan is None or _single(plan, P.TableScan) is None:
        return None
    if _est_side(executor, scan) <= memory_limit:
        # the scan side fits, but reserve-before-dispatch gates on the
        # whole compiled program (devgen temporaries included) — spill
        # rather than let the in-core path fail its HBM reservation
        from .streaming import estimate_program_bytes

        if estimate_program_bytes(executor, plan) <= memory_limit:
            return None
    return (sort, scan)


def execute_spilled_sort(executor, plan, sort, scan):
    """Device-sorted runs merged HOST-side (FileSingleStreamSpiller +
    MergeOperator roles): each split batch sorts on device, the final
    total order comes from one stable host lexsort over the concatenated
    runs' transformed keys — device memory never holds more than a batch.
    Cross-batch varchar dictionaries are UNIFIED by merge_pages_to_arrays
    (codes remapped) before any rank transform."""
    import numpy as np

    from ..page import Column, Page
    from .fragment_exec import FragmentExecutor
    from .local import merge_pages_to_arrays

    limit = int(executor.config.get("memory_limit_bytes"))
    syms = tuple(sort.output_symbols())
    types_map = sort.output_types()
    pages, _, _ = _side_pages(
        executor, P.Sort(sort.source, sort.keys), scan, limit
    )
    dicts: Dict[str, object] = {}
    merged, total = merge_pages_to_arrays(
        pages, list(syms), [(s, types_map[s]) for s in syms], dicts
    )
    # host lexsort: last key is primary
    lex = []
    for k in reversed(sort.keys):
        vals, oks = merged[k.column]
        if oks is None:
            oks = np.ones(total, bool)
        d = dicts.get(k.column)
        if d is not None:
            # dictionary codes -> DENSE lexicographic ranks (duplicate
            # values under distinct codes must tie so later keys apply)
            dd = np.asarray(d).astype(str)
            order = np.argsort(dd, kind="stable")
            sd = dd[order]
            dense = np.zeros(len(order), dtype=np.int64)
            if len(order) > 1:
                dense[1:] = np.cumsum(sd[1:] != sd[:-1])
            rank = np.empty(len(order), dtype=np.int64)
            rank[order] = dense
            safe = np.clip(vals, 0, max(len(order) - 1, 0)).astype(np.int64)
            v = rank[safe]
        else:
            v = vals
        if v.ndim == 2:
            # wide (two-limb) decimal key: minor operand = low limb in
            # unsigned order (sign bit flipped into the signed domain),
            # major = signed high limb; DESC complements both
            lo = v[:, 0].astype(np.int64) ^ np.int64(-(2**63))
            hi = v[:, 1].astype(np.int64)
            if not k.ascending:
                lo, hi = ~lo, ~hi
            lex.append(lo)
            lex.append(hi)
        else:
            if not k.ascending:
                # ints reverse via bitwise complement (negation wraps at
                # INT64_MIN, so it would sort first under DESC); floats
                # negate
                v = -v if v.dtype.kind == "f" else ~v.astype(np.int64)
            lex.append(v)
        nullbit = ~oks if not k.nulls_first else oks
        lex.append(nullbit)
    idx = np.lexsort(lex) if lex else np.arange(total)
    cols = []
    for sym in syms:
        vals, oks = merged[sym]
        if oks is None:
            oks = np.ones(total, bool)
        cols.append(
            Column(
                types_map[sym], vals[idx],
                None if oks.all() else oks[idx],
                dicts.get(sym),
            )
        )
    sorted_page = Page(cols, total, list(syms))
    if plan.source is sort:
        # nothing above the sort: emit the merged page directly instead of
        # round-tripping the full result through device memory
        out_cols = [
            sorted_page.columns[syms.index(s)] for s in plan.symbols
        ]
        return Page(out_cols, total, list(plan.names))
    cfg, orig_remote, dyn = _spill_ctx(executor)
    rewritten = _replace_aggregate(
        plan, sort,
        P.RemoteSource(SORT_RUNS_ID, syms, tuple(types_map.items())),
    )
    merged_remote = dict(orig_remote)
    merged_remote[SORT_RUNS_ID] = [sorted_page]
    final = FragmentExecutor(
        executor.catalogs, cfg, {}, merged_remote, dyn
    )
    return final.execute(rewritten)


def plan_window_spill(executor, plan: P.Output, memory_limit: int):
    win = _single(plan, P.Window)
    if win is None or not win.partition_by:
        return None
    scan = _is_scan_chain(win.source)
    if scan is None or _single(plan, P.TableScan) is None:
        return None
    est = _est_side(executor, scan)
    if est <= memory_limit:
        from .streaming import estimate_program_bytes

        if estimate_program_bytes(executor, plan) <= memory_limit:
            return None
        est = max(est, float(memory_limit))
    npart = max(2, math.ceil(est * 2 / memory_limit))
    return (win, scan, npart)


def execute_spilled_window(executor, plan, win, scan, npart):
    """Hash-partition rows by PARTITION BY keys host-side, run the window
    per partition batch on device (window partitions never straddle hash
    partitions), concatenate outputs."""
    import dataclasses

    from ..exec.partitioner import partition_page
    from .fragment_exec import FragmentExecutor

    limit = int(executor.config.get("memory_limit_bytes"))
    pages, syms, types_ = _side_pages(executor, win.source, scan, limit)
    parts: List[List] = [[] for _ in range(npart)]
    for page in pages:
        for p, sub in enumerate(
            partition_page(page, list(win.partition_by), npart)
        ):
            if sub.count:
                parts[p].append(sub)
    wsyms = tuple(win.output_symbols())
    wtypes = tuple(win.output_types().items())
    win_sub = dataclasses.replace(
        win, source=P.RemoteSource(WINDOW_SRC_ID, syms, types_)
    )
    wplan = P.Output(win_sub, wsyms, wsyms)
    cfg, orig_remote, dyn = _spill_ctx(executor)
    out_pages = []
    for p in range(npart):
        if not parts[p]:
            continue
        remote = dict(orig_remote)
        remote[WINDOW_SRC_ID] = parts[p]
        sub = FragmentExecutor(executor.catalogs, cfg, {}, remote, dyn)
        page = sub.execute(wplan)
        if page.count:
            out_pages.append(page)
    rewritten = _replace_aggregate(
        plan, win, P.RemoteSource(JOIN_OUT_ID, wsyms, wtypes)
    )
    merged_remote = dict(orig_remote)
    merged_remote[JOIN_OUT_ID] = out_pages
    final = FragmentExecutor(
        executor.catalogs, cfg, {}, merged_remote, dyn
    )
    return final.execute(rewritten)


def plan_distinct_spill(executor, plan: P.Output, memory_limit: int):
    """Out-of-core DISTINCT aggregation — the LOCAL analog of the mesh
    path's hash repartition by grouping keys: a single-step Aggregate
    carrying distinct aggregates over a scan chain whose working set
    exceeds the limit.  GROUP BY queries hash-partition rows by their
    grouping keys (groups never straddle partitions, so per-partition
    single-step aggregation is exact for ANY aggregate, distinct
    included); global queries spill per-argument distinct state to host
    arrays and count it there."""
    agg = _single(plan, P.Aggregate)
    if agg is None or agg.step != "single":
        return None
    if not any(a.distinct for a in agg.aggs):
        return None
    scan = _is_scan_chain(agg.source)
    if scan is None or _single(plan, P.TableScan) is None:
        return None
    if getattr(executor, "splits_by_scan", None) is not None:
        # fragment task: the distributed planner already hash-partitions
        # distinct aggregation across tasks; this rewrite re-enumerates
        # whole-table splits and would double-count another task's rows
        return None
    if not agg.keys:
        # global: only count(DISTINCT x) reduces to host-side uniques;
        # the remaining aggs must merge through partial/final kernels
        for a in agg.aggs:
            if a.distinct and (
                a.kind != "count" or a.arg is None or a.arg2 is not None
            ):
                return None
            if not a.distinct and not a.partializable:
                return None
    est = _est_side(executor, scan)
    if est <= memory_limit:
        from .streaming import estimate_program_bytes

        if estimate_program_bytes(executor, plan) <= memory_limit:
            return None
        est = max(est, float(memory_limit))
    npart = max(2, math.ceil(est * 2 / memory_limit))
    return (agg, scan, npart)


def _host_distinct_count(pages, sym, typ):
    """Union per-batch deduped pages (the spilled distinct state) in host
    arrays and count distinct non-null values.  Varchar compares by STRING
    VALUE, not dictionary code — batches may dictionary-encode the same
    string under different codes, and merge_pages_to_arrays only unifies
    (it does not dedup) the merged dictionary."""
    import numpy as np

    from .local import merge_pages_to_arrays

    dicts: Dict[str, object] = {}
    merged, total = merge_pages_to_arrays(pages, [sym], [(sym, typ)], dicts)
    vals, oks = merged[sym]
    if oks is not None:
        vals = vals[oks] if vals.ndim == 1 else vals[oks, :]
    d = dicts.get(sym)
    if d is not None:
        safe = np.clip(vals, 0, max(len(d) - 1, 0)).astype(np.int64)
        return int(len(np.unique(np.asarray(d).astype(str)[safe])))
    if vals.ndim == 2:  # wide decimal: a value is its (lo, hi) limb pair
        return int(len(np.unique(vals, axis=0)))
    return int(len(np.unique(vals)))


def execute_spilled_distinct(executor, plan, agg, scan, npart):
    """Grouped: hash-partition rows by GROUP BY keys host-side, run the
    ORIGINAL single-step aggregate (distinct and all) per partition —
    groups are disjoint across partitions so concatenation is exact.
    Global: per split batch run a device Distinct over each
    count(DISTINCT) argument; the batches' deduped values are the spilled
    distinct state, unioned and counted in host arrays, while any
    non-distinct aggs merge through the ordinary partial/final spill."""
    import dataclasses

    from ..expr import ir
    from ..page import Page, column_from_pylist
    from .fragment_exec import FragmentExecutor

    limit = int(executor.config.get("memory_limit_bytes"))
    cfg, orig_remote, dyn = _spill_ctx(executor)
    syms = tuple(agg.output_symbols())
    types_map = agg.output_types()

    if agg.keys:
        from ..exec.partitioner import partition_page

        pages, src_syms, src_types = _side_pages(
            executor, agg.source, scan, limit
        )
        parts: List[List] = [[] for _ in range(npart)]
        for page in pages:
            for p, sub in enumerate(
                partition_page(page, list(agg.keys), npart)
            ):
                if sub.count:
                    parts[p].append(sub)
        agg_sub = dataclasses.replace(
            agg,
            source=P.RemoteSource(DISTINCT_SRC_ID, src_syms, src_types),
        )
        aplan = P.Output(agg_sub, syms, syms)
        out_pages = []
        for p in range(npart):
            if not parts[p]:
                continue
            remote = dict(orig_remote)
            remote[DISTINCT_SRC_ID] = parts[p]
            sub = FragmentExecutor(executor.catalogs, cfg, {}, remote, dyn)
            page = sub.execute(aplan)
            if page.count:
                out_pages.append(page)
    else:
        conn = executor.catalogs.get(scan.catalog)
        batch_budget = max(limit // SAFETY_FACTOR, 1)
        nbatches = max(
            1, math.ceil(_est_side(executor, scan) / batch_budget)
        )
        splits = conn.split_manager().get_splits(
            scan.table, nbatches, scan.constraint
        )
        batch = max(1, len(splits) // nbatches)
        src_types = agg.source.output_types()
        d_cols = sorted({a.arg for a in agg.aggs if a.distinct})
        state: Dict[str, List[Page]] = {c: [] for c in d_cols}
        dplans = {
            c: P.Output(
                P.Distinct(
                    P.Project(
                        agg.source,
                        ((c, ir.ColumnRef(src_types[c], c)),),
                    )
                ),
                (c,), (c,),
            )
            for c in d_cols
        }
        for start in range(0, max(len(splits), 1), batch):
            bsplits = splits[start : start + batch]
            for c in d_cols:
                sub = FragmentExecutor(
                    executor.catalogs, cfg, {0: bsplits}, orig_remote, dyn
                )
                state[c].append(sub.execute(dplans[c]))
        counts = {
            c: _host_distinct_count(state[c], c, src_types[c])
            for c in d_cols
        }
        nd_aggs = tuple(a for a in agg.aggs if not a.distinct)
        nd_page = None
        if nd_aggs:
            # the remaining (partializable) aggs merge through the same
            # partial/final kernels the exchange uses, per split batch
            nd_partial = P.Aggregate(agg.source, (), nd_aggs, "partial")
            psyms = tuple(nd_partial.output_symbols())
            pplan = P.Output(nd_partial, psyms, psyms)
            partial_pages = []
            for start in range(0, max(len(splits), 1), batch):
                sub = FragmentExecutor(
                    executor.catalogs, cfg,
                    {0: splits[start : start + batch]}, orig_remote, dyn,
                )
                partial_pages.append(sub.execute(pplan))
            nd_final = P.Aggregate(
                P.RemoteSource(
                    SPILL_SOURCE_ID, psyms,
                    tuple(nd_partial.output_types().items()),
                ),
                (), nd_aggs, "final",
            )
            nd_syms = tuple(nd_final.output_symbols())
            remote = dict(orig_remote)
            remote[SPILL_SOURCE_ID] = partial_pages
            sub = FragmentExecutor(executor.catalogs, cfg, {}, remote, dyn)
            nd_page = sub.execute(P.Output(nd_final, nd_syms, nd_syms))
        cols = []
        for a in agg.aggs:
            if a.distinct:
                cols.append(
                    column_from_pylist(a.output_type, [counts[a.arg]])
                )
            else:
                cols.append(nd_page.by_name(a.output))
        out_pages = [Page(cols, 1, list(syms))]

    rewritten = _replace_aggregate(
        plan, agg, P.RemoteSource(JOIN_OUT_ID, syms, tuple(types_map.items()))
    )
    merged_remote = dict(orig_remote)
    merged_remote[JOIN_OUT_ID] = out_pages
    final = FragmentExecutor(
        executor.catalogs, cfg, {}, merged_remote, dyn
    )
    return final.execute(rewritten)
