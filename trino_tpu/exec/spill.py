"""Out-of-core (spilled) aggregation: split-batched partials merged on host.

Reference parity: spiller/ (FileSingleStreamSpiller feeding
SpillableHashAggregationBuilder -> MergingHashAggregationBuilder) triggered
by memory/MemoryRevokingScheduler.java:47 when revocable memory exceeds the
pool.  The reference serializes agg-builder state to local disk and merges
sorted runs; the TPU-native analog keeps HBM as the scarce tier and *host
RAM as the spill target* (SURVEY §7 step 7): scan splits are processed in
batches sized to the memory limit, each batch's PARTIAL aggregation output
(small accumulator pages) is retained on the host, and one final
FINAL/INTERMEDIATE merge runs over the concatenated partial pages.

The same partial/final kernels used by the distributed exchange do the
merging, so spill shares its correctness surface with multi-node execution.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..plan import nodes as P

SPILL_SOURCE_ID = -1  # RemoteSource id for in-process spilled partials
SAFETY_FACTOR = 4  # batch working-set headroom under the limit


def find_spillable_aggregate(
    plan: P.Output,
) -> Optional[Tuple[P.Aggregate, P.TableScan]]:
    """Match a plan whose (unique) Aggregate sits over a pure scan chain
    (Filter/Project only down to one TableScan) and is partializable.
    Anything above the Aggregate is fine — it runs after the merge."""
    found: List[P.Aggregate] = []

    def find_agg(n: P.PlanNode):
        if isinstance(n, P.Aggregate):
            found.append(n)
            return
        for s in n.sources:
            find_agg(s)

    find_agg(plan)
    if len(found) != 1:
        return None
    agg = found[0]
    if agg.step not in ("single", "partial"):
        return None
    if not all(a.partializable for a in agg.aggs):
        return None
    node = agg.source
    while isinstance(node, (P.Filter, P.Project)):
        node = node.source
    if not isinstance(node, P.TableScan):
        return None
    # the aggregate's scan must be the plan's only scan: the rewritten plan
    # replaces the whole scan chain, so remaining scans would lose their
    # split assignment
    nscans = [0]

    def count_scans(n: P.PlanNode):
        if isinstance(n, P.TableScan):
            nscans[0] += 1
        for s in n.sources:
            count_scans(s)

    count_scans(plan)
    if nscans[0] != 1:
        return None
    return agg, node


def scan_row_bytes(scan: P.TableScan) -> int:
    return sum(t.np_dtype.itemsize + 1 for _, t in scan.types)


def _replace_aggregate(
    node: P.PlanNode, agg: P.Aggregate, replacement: P.PlanNode
) -> P.PlanNode:
    if node is agg:
        return replacement
    new_sources = tuple(
        _replace_aggregate(s, agg, replacement) for s in node.sources
    )
    if all(a is b for a, b in zip(new_sources, node.sources)):
        return node
    import dataclasses

    if isinstance(node, P.SetOperation):
        return dataclasses.replace(node, inputs=new_sources)
    # other plan nodes hold their sources as individual PlanNode fields in
    # declaration order matching .sources
    updates = {}
    src_iter = iter(new_sources)
    for f in dataclasses.fields(node):
        if isinstance(getattr(node, f.name), P.PlanNode):
            updates[f.name] = next(src_iter)
    return dataclasses.replace(node, **updates)


def execute_spilled_aggregation(
    executor,  # LocalExecutor or FragmentExecutor (late import cycle)
    plan: P.Output,
    agg: P.Aggregate,
    scan: P.TableScan,
    splits: List,
    batch_size: int,
):
    """Run the scan->partial-agg pipeline per split batch, keep partial
    pages on host, then run the rewritten plan (Aggregate replaced by a
    merge over the spilled partials)."""
    from .fragment_exec import FragmentExecutor

    partial = P.Aggregate(agg.source, agg.keys, agg.aggs, "partial")
    syms = tuple(partial.output_symbols())
    partial_plan = P.Output(partial, syms, syms)

    # the plan's only scan is preorder index 0 in both the original fragment
    # and the partial subplan, so collected dynamic filters carry over
    dyn_filters = getattr(executor, "dynamic_filters", None)
    orig_remote = dict(getattr(executor, "remote_pages", {}) or {})

    partial_pages = []
    rows_pruned = 0
    scan_bytes = 0
    batch_config = dict(executor.config)
    batch_config.pop("memory_limit_bytes", None)  # batches are pre-sized
    for start in range(0, max(len(splits), 1), batch_size):
        batch = splits[start : start + batch_size]
        sub = FragmentExecutor(
            executor.catalogs, batch_config, {0: batch}, orig_remote,
            dyn_filters,
        )
        partial_pages.append(sub.execute(partial_plan))
        rows_pruned += sub.df_rows_pruned
        scan_bytes += sub.scan_bytes

    merged_step = "final" if agg.step == "single" else "intermediate"
    rs = P.RemoteSource(
        SPILL_SOURCE_ID, syms, tuple(partial.output_types().items())
    )
    merged = P.Aggregate(rs, agg.keys, agg.aggs, merged_step)
    rewritten = _replace_aggregate(plan, agg, merged)

    # the rewritten plan has no TableScan (single-scan precondition) but may
    # still hold RemoteSources above the aggregate (e.g. a broadcast build
    # side of a join over the agg) — keep the fragment's original pages
    merged_remote = dict(orig_remote)
    merged_remote[SPILL_SOURCE_ID] = partial_pages
    final_ex = FragmentExecutor(
        executor.catalogs, batch_config, {}, merged_remote
    )
    page = final_ex.execute(rewritten)
    # surface batch stats on the outer executor (task info reporting)
    executor.df_rows_pruned = rows_pruned
    executor.scan_bytes = scan_bytes
    return page


def plan_spill(
    executor,
    plan: P.Output,
    memory_limit: int,
) -> Optional[Tuple[P.Aggregate, P.TableScan, List, int]]:
    """Decide whether to spill: returns (agg, scan, splits, batch_size) when
    the estimated scan working set exceeds the limit (the same threshold
    _account_memory enforces) and the plan shape allows out-of-core
    aggregation.  Batches are sized to limit/SAFETY_FACTOR so each batch
    plus kernel temporaries stays under the limit."""
    match = find_spillable_aggregate(plan)
    if match is None:
        return None
    agg, scan = match
    conn = executor.catalogs.get(scan.catalog)
    est_table = conn.metadata().get_table_statistics(
        scan.table
    ).row_count * scan_row_bytes(scan)
    batch_budget = max(memory_limit // SAFETY_FACTOR, 1)

    splits_map: Dict[int, List] = getattr(executor, "splits_by_scan", None)
    if splits_map is not None:
        # fragment executor: this task's assigned splits of the (single,
        # preorder-index-0) scan
        splits = splits_map.get(0, [])
        if not splits:
            return None
        est = est_table * len(splits) / max(splits[0].total, 1)
        if est <= memory_limit:
            return None
        per_split = est / len(splits)
        batch = max(1, int(batch_budget / max(per_split, 1)))
        if batch >= len(splits):
            return None
        return agg, scan, splits, batch
    if est_table <= memory_limit:
        return None
    nbatches = math.ceil(est_table / batch_budget)
    splits = conn.split_manager().get_splits(
        scan.table, nbatches, scan.constraint
    )
    if len(splits) <= 1:
        return None
    return agg, scan, splits, max(1, len(splits) // nbatches)
