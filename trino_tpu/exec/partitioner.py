"""Output page partitioning for the exchange (shuffle-write).

Reference parity: operator/output/PagePartitioner.java:55 (partitionPage:134)
and the PositionsAppender family — rows of an output page are routed to one
buffer per consumer task by a hash of the partition keys; broadcast/single
replicate or pass through (BroadcastOutputBuffer / PartitionedOutputBuffer).

Hashing is vectorized numpy on the host (pages are already materialized at
the fragment boundary); dictionary-coded varchar keys hash their *string*
values so codes assigned by different producers agree.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..page import Column, Page

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (vectorized)."""
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9) & _M64
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB) & _M64
        return x ^ (x >> np.uint64(31))


def _fnv_str(s: str) -> int:
    h = 0xCBF29CE484222325
    for ch in s.encode():
        h = ((h ^ ch) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def hash_rows(page: Page, keys: Sequence[str]) -> np.ndarray:
    """uint64 partition hash per row over the named key columns."""
    n = page.count
    h = np.full(n, 0x9E3779B97F4A7C15, dtype=np.uint64)
    for k in keys:
        col = page.by_name(k)
        vals = np.asarray(col.values)[:n]
        if col.dictionary is not None:
            # hash string values for cross-producer stability
            dict_hash = np.array(
                [_fnv_str(str(s)) for s in col.dictionary], dtype=np.uint64
            )
            safe = np.clip(vals, 0, max(len(dict_hash) - 1, 0))
            ch = np.where(
                vals >= 0,
                dict_hash[safe] if len(dict_hash) else np.uint64(0),
                np.uint64(0),
            )
        elif vals.ndim == 2:
            # wide (two-limb) decimal: fold both limbs into one chunk
            with np.errstate(over="ignore"):
                ch = _mix64(
                    vals[:, 0].astype(np.int64).view(np.uint64)
                    ^ (vals[:, 1].astype(np.int64).view(np.uint64)
                       * np.uint64(0x9E3779B97F4A7C15))
                )
        elif vals.dtype.kind == "f":
            ch = _mix64(vals.view(np.uint64) if vals.dtype == np.float64
                        else vals.astype(np.float64).view(np.uint64))
        else:
            ch = _mix64(vals.astype(np.int64).view(np.uint64))
        if col.validity is not None:
            ch = np.where(np.asarray(col.validity)[:n], ch, np.uint64(0))
        with np.errstate(over="ignore"):
            h = (h * np.uint64(31) + ch) & _M64
    return _mix64(h)


def take_rows(page: Page, idx: np.ndarray) -> Page:
    cols = []
    for c in page.columns:
        vals = np.asarray(c.values)[:page.count][idx]
        ok = (
            None
            if c.validity is None
            else np.asarray(c.validity)[:page.count][idx]
        )
        cols.append(Column(c.type, vals, ok, c.dictionary))
    return Page(cols, len(idx), page.names)


def partition_page(page: Page, keys: Sequence[str], nparts: int) -> List[Page]:
    """Split a page into nparts pages by hash(keys) % nparts."""
    if nparts == 1:
        return [page]
    part = (hash_rows(page, keys) % np.uint64(nparts)).astype(np.int64)
    return [take_rows(page, np.nonzero(part == p)[0]) for p in range(nparts)]


def partition_page_round_robin(page: Page, nparts: int) -> List[Page]:
    """Split a page into nparts pages row-round-robin (RandomExchanger /
    FIXED_ARBITRARY_DISTRIBUTION): balances load with no key affinity."""
    if nparts == 1:
        return [page]
    idx = np.arange(page.count)
    return [take_rows(page, idx[p::nparts]) for p in range(nparts)]


def chunk_page(page: Page, rows_per_chunk: int = 65536) -> List[Page]:
    """Split a page into bounded-size wire chunks (output buffer frames)."""
    if page.count <= rows_per_chunk:
        return [page]
    out = []
    for start in range(0, page.count, rows_per_chunk):
        idx = np.arange(start, min(start + rows_per_chunk, page.count))
        out.append(take_rows(page, idx))
    return out


def concat_pages(pages: List[Page]) -> Page:
    """Concatenate pages with identical schema (single-producer merge)."""
    assert pages, "no pages"
    if len(pages) == 1:
        return pages[0]
    first = pages[0]
    cols = []
    for i in range(first.num_columns):
        vals = np.concatenate(
            [np.asarray(p.columns[i].values)[: p.count] for p in pages]
        )
        oks = [
            np.ones(p.count, bool)
            if p.columns[i].validity is None
            else np.asarray(p.columns[i].validity)[: p.count]
            for p in pages
        ]
        ok = np.concatenate(oks)
        cols.append(
            Column(
                first.columns[i].type,
                vals,
                None if ok.all() else ok,
                first.columns[i].dictionary,
            )
        )
    return Page(cols, sum(p.count for p in pages), first.names)


class SkewedPartitionRebalancer:
    """Skew-aware partition assignment for scaled writes.

    Reference parity: operator/output/SkewedPartitionRebalancer.java:55 +
    ScaleWriterPartitioningExchanger — when one partition receives a
    disproportionate share of the rows, it is assigned EXTRA writers and
    its rows round-robin across them.  Writer affinity is a clustering
    preference for writes, not a correctness requirement, so splitting a
    hot partition is safe (the reference applies the same relaxation).

    Stateful across pages: observed per-partition row counts accumulate,
    and every `rebalance_interval` rows the hottest partitions (above
    `skew_factor` x the mean) get one more bucket each, drawn from the
    least-loaded buckets.
    """

    def __init__(self, nparts: int, skew_factor: float = 2.0,
                 rebalance_interval: int = 65536):
        self.nparts = nparts
        self.skew_factor = skew_factor
        self.rebalance_interval = rebalance_interval
        self.part_rows = np.zeros(nparts, dtype=np.int64)
        self.bucket_rows = np.zeros(nparts, dtype=np.int64)
        # partition -> list of buckets its rows cycle through
        self.assignments: List[List[int]] = [[p] for p in range(nparts)]
        self._since_rebalance = 0
        self._rr = np.zeros(nparts, dtype=np.int64)

    def scaled_partitions(self) -> List[int]:
        return [p for p, a in enumerate(self.assignments) if len(a) > 1]

    def _maybe_rebalance(self):
        if self._since_rebalance < self.rebalance_interval:
            return
        self._since_rebalance = 0
        total = self.part_rows.sum()
        if total == 0:
            return
        mean = total / self.nparts
        for p in np.argsort(-self.part_rows):
            if self.part_rows[p] <= self.skew_factor * mean:
                break
            if len(self.assignments[p]) >= self.nparts:
                continue
            # grant the least-loaded bucket not already assigned
            for b in np.argsort(self.bucket_rows):
                if int(b) not in self.assignments[p]:
                    self.assignments[p].append(int(b))
                    break

    def assign(self, page: Page, keys: Sequence[str]) -> np.ndarray:
        """Per-row OUTPUT bucket; hot partitions cycle their buckets."""
        part = (
            hash_rows(page, keys) % np.uint64(self.nparts)
        ).astype(np.int64)
        np.add.at(self.part_rows, part, 1)
        self._since_rebalance += page.count
        self._maybe_rebalance()
        bucket = part.copy()
        for p in self.scaled_partitions():
            rows = np.nonzero(part == p)[0]
            if len(rows) == 0:
                continue
            buckets = np.array(self.assignments[p], dtype=np.int64)
            offs = (self._rr[p] + np.arange(len(rows))) % len(buckets)
            bucket[rows] = buckets[offs]
            self._rr[p] += len(rows)
        np.add.at(self.bucket_rows, bucket, 1)
        return bucket

    def partition_page(self, page: Page, keys: Sequence[str]) -> List[Page]:
        """Feed the page through assign() in rebalance_interval-sized
        chunks so hot partitions can escalate to MULTIPLE extra buckets
        within one large write (a single assign call would rebalance at
        most once)."""
        buckets = np.empty(page.count, dtype=np.int64)
        step = self.rebalance_interval
        for start in range(0, page.count, step):
            idx = np.arange(start, min(start + step, page.count))
            buckets[idx] = self.assign(take_rows(page, idx), keys)
        return [
            take_rows(page, np.nonzero(buckets == b)[0])
            for b in range(self.nparts)
        ]
