"""Dynamic filtering: build-side domains prune probe-side scans.

Reference parity: server/DynamicFilterService.java:105 (domain summary as a
TupleDomain union), worker-side collection from the join build
(DynamicFilterSourceOperator + JoinDomainBuilder), local application for
broadcast joins (LocalDynamicFiltersCollector), and pushdown into the scan
via the DynamicFilter SPI so the connector prunes rows/splits.

TPU-first placement: in this engine a fragment's build side arrives as
whole exchange pages *before* the probe fragment's XLA program runs, so
domains are computed host-side from the received build pages and applied to
probe scan arrays during load — rows are pruned before they ever occupy
padded device tiles, shrinking both HBM footprint and kernel shapes.

Safety: domains are only derived for INNER equi-joins (probe side may drop
non-matching rows) and for semi-joins whose mark is consumed as a positive
filter directly above; pushdown only descends row-preserving edges
(Filter/Project/inner-probe/Aggregate-group-key/semi-join-source).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..expr import ir
from ..page import Page
from ..plan import nodes as P

MAX_DISCRETE_VALUES = 100_000


@dataclasses.dataclass
class Domain:
    """Value domain of one build key (spi/predicate/Domain analog)."""

    lo: Optional[float] = None
    hi: Optional[float] = None
    values: Optional[np.ndarray] = None  # discrete int64 set (sorted)
    strings: Optional[Set[str]] = None  # for dictionary columns

    def keep_mask(self, vals: np.ndarray, dictionary=None) -> np.ndarray:
        if self.strings is not None:
            if dictionary is None:
                return np.ones(len(vals), bool)
            ok_code = np.array(
                [str(s) in self.strings for s in dictionary], dtype=bool
            )
            safe = np.clip(vals, 0, max(len(dictionary) - 1, 0))
            return np.where(vals >= 0, ok_code[safe], False)
        keep = np.ones(len(vals), bool)
        if self.lo is not None:
            keep &= vals >= self.lo
        if self.hi is not None:
            keep &= vals <= self.hi
        if self.values is not None:
            keep &= np.isin(vals.astype(np.int64), self.values)
        return keep


def _domain_from_pages(pages: List[Page], symbol: str) -> Optional[Domain]:
    vals_parts, str_set = [], set()
    is_dict = False
    for p in pages:
        if p.count == 0:
            continue
        col = p.by_name(symbol)
        v = np.asarray(col.values)[: p.count]
        if col.validity is not None:
            v = v[np.asarray(col.validity)[: p.count]]
        if col.dictionary is not None:
            is_dict = True
            codes = v[v >= 0]
            str_set.update(str(col.dictionary[c]) for c in np.unique(codes))
        else:
            vals_parts.append(v)
    if is_dict:
        return Domain(strings=str_set)
    if not vals_parts:
        return Domain(lo=1, hi=0)  # empty build side: prune everything
    vals = np.concatenate(vals_parts)
    if vals.dtype.kind not in ("i", "u", "f", "b"):
        return None
    if vals.dtype.kind == "f":
        # NaN build keys never equal any probe key: exclude them from the
        # domain (all-NaN build means nothing can match)
        vals = vals[~np.isnan(vals)]
        if len(vals) == 0:
            return Domain(lo=1, hi=0)
    d = Domain(lo=vals.min(), hi=vals.max())
    if vals.dtype.kind in ("i", "u") and len(vals) <= MAX_DISCRETE_VALUES:
        d.values = np.unique(vals.astype(np.int64))
    return d


def _positive_filter_marks(predicate: ir.Expr) -> Set[str]:
    """Mark symbols required true by a filter predicate (conjuncts that are
    bare ColumnRefs)."""
    out: Set[str] = set()

    def conjuncts(e: ir.Expr):
        if isinstance(e, ir.Logical) and e.op == "and":
            for t in e.terms:
                conjuncts(t)
        else:
            if isinstance(e, ir.ColumnRef):
                out.add(e.name)

    conjuncts(predicate)
    return out


def collect_dynamic_filters(
    plan: P.PlanNode, remote_pages: Dict[int, List[Page]]
) -> Dict[Tuple[int, str], List[Domain]]:
    """Walk a fragment plan; returns {(scan_preorder_index, scan_symbol):
    [domains]} for probe keys whose build side is a RemoteSource with
    fetched pages."""
    # preorder scan indexing must match FragmentExecutor._load_walk
    scan_index: Dict[int, int] = {}
    counter = [0]

    def index_scans(n: P.PlanNode):
        if isinstance(n, P.TableScan):
            scan_index[id(n)] = counter[0]
            counter[0] += 1
        for s in n.sources:
            index_scans(s)

    index_scans(plan)

    out: Dict[Tuple[int, str], List[Domain]] = {}

    def push_down(node: P.PlanNode, symbol: str, domain: Domain):
        """Descend row-preserving edges to the defining TableScan."""
        if isinstance(node, P.TableScan):
            if symbol in node.output_symbols():
                out.setdefault((scan_index[id(node)], symbol), []).append(
                    domain
                )
            return
        if isinstance(node, P.Filter):
            push_down(node.source, symbol, domain)
            return
        if isinstance(node, P.Project):
            for sym, e in node.assignments:
                if sym == symbol:
                    if isinstance(e, ir.ColumnRef):
                        push_down(node.source, e.name, domain)
                    return
            return
        if isinstance(node, P.Join):
            if node.kind == "inner" and symbol in node.left.output_symbols():
                push_down(node.left, symbol, domain)
            return
        if isinstance(node, P.SemiJoin):
            if symbol in node.source.output_symbols():
                push_down(node.source, symbol, domain)
            return
        if isinstance(node, P.Aggregate):
            if symbol in node.keys:
                push_down(node.source, symbol, domain)
            return
        # Sort/TopN/Limit/Window/SetOperation/...: stop (row sets or
        # ordering-sensitive below; pruning there could change results)

    def walk(node: P.PlanNode, positive_marks: Set[str]):
        if isinstance(node, P.Filter):
            walk(node.source,
                 positive_marks | _positive_filter_marks(node.predicate))
            return
        if isinstance(node, P.Join) and node.kind == "inner":
            if isinstance(node.right, P.RemoteSource):
                pages = remote_pages.get(node.right.fragment_id, [])
                for probe_sym, build_sym in node.criteria:
                    d = _domain_from_pages(pages, build_sym)
                    if d is not None:
                        push_down(node.left, probe_sym, d)
        if isinstance(node, P.SemiJoin) and node.output in positive_marks:
            if isinstance(node.filtering, P.RemoteSource):
                pages = remote_pages.get(node.filtering.fragment_id, [])
                for src_sym, filt_sym in zip(
                    node.source_keys, node.filtering_keys
                ):
                    d = _domain_from_pages(pages, filt_sym)
                    if d is not None:
                        push_down(node.source, src_sym, d)
        for s in node.sources:
            walk(s, set())

    walk(plan, set())
    return out
