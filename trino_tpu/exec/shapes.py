"""The engine-wide bucketed-batch ABI: one home for shape quantization.

Every fragment input shape the engine traces is quantized here before
it reaches XLA.  Historically each call site rounded row counts up to
the next multiple of the TPU lane width (128) independently — so every
distinct split size was a distinct padded shape, hence a distinct
compiled program, and the compile cache only helped when traffic
repeated *exact* sizes.  The :class:`PaddingLadder` replaces that with
a small monotone set of rungs (geometric by default, census-tuned via
``scripts/bucket_ladder.py --emit``): arbitrary sizes collapse onto a
handful of shapes per kernel family, bounding both the number of
compiled programs (|ladder| per family) and the padded-vs-actual waste
(≤ the inter-rung ratio, 2x for the geometric ladder).

Correctness does not depend on the rung chosen: executors thread the
true row count alongside the padded buffers (the ``__count__`` traced
scalar) and mask with ``arange(cap) < count``, so any capacity ≥ count
is byte-identical.  The ladder only decides how much slack rides along.

This module must stay import-light (stdlib only): it is imported by
``exec/local.py``, ``exec/streaming.py``, ``parallel/mesh_executor.py``,
``cache/signature.py`` and the observatory, and must never create an
import cycle.

The ``((n + lane - 1) // lane) * lane`` idiom is permitted ONLY in this
file — ``scripts/check_pad_discipline.py`` lints the rest of the tree
for ad-hoc copies.
"""
from __future__ import annotations

import json
from bisect import bisect_left
from typing import Dict, Iterable, Optional, Sequence, Tuple

DEFAULT_LANE = 128

# geometric ladder spans 128 .. ~1B rows; above the top rung quantize()
# continues doubling, so the program count stays bounded at any scale
_GEOMETRIC_TOP = 1 << 30


def lane_align(n: int, lane: int = DEFAULT_LANE) -> int:
    """Round ``n`` up to the next multiple of ``lane`` (min ``lane``).

    The single permitted home of the next-multiple idiom; every other
    module quantizes through a :class:`PaddingLadder` (whose "off" mode
    degenerates to this function).
    """
    n = int(n)
    if n <= lane:
        return lane
    return ((n + lane - 1) // lane) * lane


class PaddingLadder:
    """A monotone set of lane-aligned capacities that row counts
    quantize onto before tracing.

    ``rungs == ()`` is the legacy escape hatch (``padding_ladder=off``):
    :meth:`quantize` degenerates to plain lane alignment and
    :meth:`size` is 0, signalling "unbounded program count" to callers
    that report ladder occupancy.
    """

    __slots__ = ("rungs", "lane", "source")

    def __init__(
        self,
        rungs: Sequence[int] = (),
        lane: int = DEFAULT_LANE,
        source: str = "explicit",
    ):
        lane = max(1, int(lane))
        cleaned = sorted({lane_align(int(r), lane) for r in rungs if int(r) > 0})
        self.rungs: Tuple[int, ...] = tuple(cleaned)
        self.lane = lane
        self.source = source

    @classmethod
    def geometric(
        cls, lane: int = DEFAULT_LANE, top: int = _GEOMETRIC_TOP
    ) -> "PaddingLadder":
        """Default rungs ``lane · 2^k`` up to ``top`` — waste ≤ 2x."""
        rungs = []
        r = lane
        while r <= top:
            rungs.append(r)
            r *= 2
        return cls(rungs, lane=lane, source="geometric")

    def quantize(self, n: int) -> int:
        """Smallest rung ≥ ``n`` (lane-aligned fallback without rungs).

        Above the top rung, capacities continue doubling from it, so a
        census-tuned ladder stays total over inputs larger than
        anything the census saw while keeping the program count
        logarithmic in the overshoot.
        """
        n = int(n)
        rungs = self.rungs
        if not rungs:
            return lane_align(n, self.lane)
        if n <= rungs[0]:
            return rungs[0]
        i = bisect_left(rungs, n)
        if i < len(rungs):
            return rungs[i]
        cap = rungs[-1]
        while cap < n:
            cap *= 2
        return cap

    def size(self) -> int:
        """Rung count — the per-family compiled-program bound (0 = off)."""
        return len(self.rungs)

    def waste(self, n: int) -> float:
        """Padded-vs-actual ratio for one observation (≥ 1.0)."""
        n = int(n)
        if n <= 0:
            return 1.0
        return self.quantize(n) / float(n)

    def describe(self) -> Dict[str, object]:
        return {
            "source": self.source,
            "lane": self.lane,
            "size": self.size(),
            "rungs": list(self.rungs),
        }

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "PaddingLadder(%s, %d rungs, lane=%d)" % (
            self.source, self.size(), self.lane,
        )


def parse_ladder_spec(
    spec: str, lane: int = DEFAULT_LANE
) -> PaddingLadder:
    """Parse the ``padding_ladder`` session property.

    ``geometric``/``auto``/``on``/empty → the default geometric ladder;
    ``off``/``none``/``lane`` → legacy pure lane alignment; otherwise a
    comma-separated rung list (``"128,1024,8192"``).
    """
    text = (spec or "").strip().lower()
    if text in ("", "geometric", "auto", "on", "default", "true"):
        return PaddingLadder.geometric(lane=lane)
    if text in ("off", "none", "lane", "false"):
        return PaddingLadder((), lane=lane, source="off")
    try:
        rungs = [int(tok) for tok in text.split(",") if tok.strip()]
    except ValueError:
        raise ValueError(
            "padding_ladder must be 'geometric', 'off', or a "
            "comma-separated rung list; got %r" % (spec,)
        )
    if not rungs:
        return PaddingLadder.geometric(lane=lane)
    return PaddingLadder(rungs, lane=lane, source="explicit")


def load_ladder_file(path: str, lane: int = DEFAULT_LANE) -> PaddingLadder:
    """Load a census-tuned ladder written by ``bucket_ladder.py --emit``.

    The file is ``{"ladder": [...], "lane": ...}`` plus advisory fields
    (wasteRatio, observations) that the engine ignores.
    """
    with open(path) as f:
        doc = json.load(f)
    rungs = doc.get("ladder") or ()
    if not rungs:
        raise ValueError("ladder file %s has no rungs" % path)
    return PaddingLadder(
        rungs, lane=int(doc.get("lane") or lane), source="census:%s" % path
    )


def resolve_ladder(config: Optional[dict]) -> PaddingLadder:
    """The executor-facing resolution order for the active ladder.

    1. a :class:`PaddingLadder` already placed in the config (the
       session resolves once and shares the object with every executor
       and streaming tile it spawns);
    2. ``padding_ladder_file`` (census-tuned, from ``--emit``);
    3. the ``padding_ladder`` spec string (default geometric).

    A missing/corrupt ladder file falls back to the spec: a worker must
    boot (and stay compile-bounded) even when the census artifact is
    stale or half-written.
    """
    cfg = config or {}
    existing = cfg.get("padding_ladder")
    if isinstance(existing, PaddingLadder):
        return existing
    path = cfg.get("padding_ladder_file")
    if path:
        try:
            return load_ladder_file(str(path))
        except (OSError, ValueError, KeyError):
            pass
    spec = existing if isinstance(existing, str) else ""
    return parse_ladder_spec(spec)


def ladder_waste(
    observations: Iterable[Tuple[int, int]], ladder: PaddingLadder
) -> Dict[str, float]:
    """Padded-vs-actual waste of ``ladder`` over ``(rows, count)``
    census observations: geometric and arithmetic means, observation-
    weighted.  The serve bench reports this against the ≤ 2x budget.
    """
    import math

    total = 0
    log_sum = 0.0
    lin_sum = 0.0
    for rows, count in observations:
        rows = int(rows)
        count = int(count)
        if rows <= 0 or count <= 0:
            continue
        w = ladder.waste(rows)
        total += count
        log_sum += math.log(w) * count
        lin_sum += w * count
    if not total:
        return {"geomean": 1.0, "mean": 1.0, "observations": 0}
    return {
        "geomean": math.exp(log_sum / total),
        "mean": lin_sum / total,
        "observations": total,
    }
