"""Fragment execution on a worker: assigned splits + remote exchange inputs.

Reference parity: execution/SqlTaskExecution.java:85 (splits -> drivers over
one fragment's operator chain) and operator/ExchangeOperator.java:44 (remote
source pages pulled from upstream tasks).  The whole fragment still compiles
to one XLA program (exec/local.py); this subclass only changes where leaf
data comes from:

  - TableScans read only the splits assigned to this task
    (SqlTaskExecution.addSplitAssignments:256)
  - RemoteSources read deserialized pages fetched by the exchange client,
    with per-producer string dictionaries merged and codes remapped (the
    engine-side analog of DictionaryBlock unnesting across tasks)
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..catalog import CatalogManager
from ..page import Page
from ..plan import nodes as P
from ..spi import Split
from .local import (
    ExecutionError,
    LocalExecutor,
    _TraceCtx,
    merge_pages_to_arrays,
)


class _FragmentTraceCtx(_TraceCtx):
    def _visit_remotesource(self, node: P.RemoteSource):
        return self._visit_tablescan(node)  # same padded-array load path


class FragmentExecutor(LocalExecutor):
    """Executes one PlanFragment's local plan for one task."""

    trace_ctx_cls = _FragmentTraceCtx

    def __init__(
        self,
        catalogs: CatalogManager,
        config: Optional[dict],
        splits_by_scan: Dict[int, List[Split]],
        remote_pages: Dict[int, List[Page]],
        dynamic_filters: Optional[Dict] = None,
    ):
        super().__init__(catalogs, config)
        self.splits_by_scan = splits_by_scan
        self.remote_pages = remote_pages
        # exchange buffers held for the whole execution (the fetched
        # pages stay referenced beside their merged copies), so they
        # count toward this task's host reservation in _account_memory
        self.exchange_bytes = sum(
            int(getattr(c.values, "nbytes", 0))
            + int(getattr(c.validity, "nbytes", 0) or 0)
            for pages in (remote_pages or {}).values()
            for p in pages
            for c in p.columns
        )
        # the bandwidth ledger accounts remote-exchange input once per
        # task (the merged arrays also feed per-dispatch inputBytes)
        if self.bandwidth_ledger is not None:
            self.bandwidth_ledger.exchange_bytes += self.exchange_bytes
        # {(scan_preorder_index, symbol): [Domain]} from exec/dynamic_filter
        self.dynamic_filters = dynamic_filters or {}
        self.df_rows_pruned = 0

    # ------------------------------------------------------------------
    def preload(self, plan: P.PlanNode) -> None:
        """Load this tile's host arrays ahead of time (background
        thread): split generation / parquet decode overlaps the previous
        tile's device compute — the double-buffered host->HBM pipeline
        (SURVEY §7 hard part 6).  Host-only: device uploads still happen
        on the execute thread."""
        scans: Dict[int, dict] = {}
        dicts: Dict[str, np.ndarray] = {}
        counts: Dict[int, int] = {}
        self._load_scans(plan, scans, dicts, counts)
        self._preloaded = (plan, scans, dicts, counts)

    def preupload(self, plan: P.PlanNode) -> None:
        """Stage this tile's device lanes from the prefetch thread: pad +
        enqueue the H2D copies (and devgen generator dispatches) NOW, so
        the transfers overlap the previous tile's kernel instead of
        serializing in front of the next dispatch.  jax transfers are
        async — this returns once the copies are enqueued, and the
        execute thread consumes the staged lanes from `_preuploaded`.
        Supervised like any other device work (mode "h2d"), so a
        transfer fault breadcrumbs and flight-records instead of wedging
        the prefetch thread silently."""
        if self._preloaded is None or self._device_fallback:
            return
        _plan, scans, _dicts, counts = self._preloaded
        staged = getattr(self, "_preuploaded", None)
        if staged is None:
            staged = self._preuploaded = {}
        for nid, arrays in scans.items():
            if nid in staged:
                continue
            node = self._scan_node_by_id(plan, nid)
            bc = self._dispatch_crumb(
                "h2d:%s" % getattr(node, "table", "remote"), "h2d",
                tree={"scan": arrays},
            )
            lanes = self._dispatch(
                lambda a=arrays, n=node, c=counts[nid], i=nid:
                    self._device_lanes(n, a, c, nid=i),
                bc,
            )
            nbytes = sum(
                int(getattr(v, "nbytes", 0) or 0)
                + int(getattr(ok, "nbytes", 0) or 0)
                for v, ok in lanes.values()
            )
            staged[nid] = lanes
            self.kernel_profile["preuploads"] = (
                self.kernel_profile.get("preuploads", 0) + 1
            )
            self.kernel_profile["preupload_bytes"] = (
                self.kernel_profile.get("preupload_bytes", 0) + nbytes
            )

    @staticmethod
    def _scan_node_by_id(plan: P.PlanNode, nid: int):
        found = [None]

        def walk(n):
            if id(n) == nid:
                found[0] = n
                return
            for s in n.sources:
                walk(s)

        walk(plan)
        return found[0]

    def _load_scans(self, node: P.PlanNode, scans, dicts, counts):
        self._scan_idx = 0
        self._load_walk(node, scans, dicts, counts)

    def _load_walk(self, node: P.PlanNode, scans, dicts, counts):
        if isinstance(node, P.TableScan):
            idx = self._scan_idx
            self._scan_idx += 1
            # shared loader from LocalExecutor, restricted to this task's
            # assigned splits
            self._load_one_scan(node, self.splits_by_scan.get(idx, []),
                                scans, dicts, counts)
            self._apply_dynamic_filters(node, idx, scans, dicts, counts)
            return
        if isinstance(node, P.RemoteSource):
            # streaming tiles re-read the SAME remote pages every tile:
            # cache the host merge AND the device upload per fragment id
            # for the run, so build tables stay HBM-resident across tiles
            cache = getattr(self, "_streaming_cache", None)
            key = None
            if cache is not None:
                # stable key: cross-run isolation comes from the fresh
                # per-run cache OBJECT; a per-run nonce here would leak
                # into the jit-cache key and recompile every warm run
                key = ("__remote__", node.fragment_id)
                hit = cache.get(key)
                if hit is not None:
                    scans[id(node)] = {
                        s: lane for s, lane in hit["merged"].items()
                    }
                    dicts.update(hit["dicts"])
                    counts[id(node)] = hit["total"]
                    self._scan_keys[id(node)] = key
                    self._scan_dictfp[id(node)] = hit.get("dictfp", 0)
                    return
            pages = self.remote_pages.get(node.fragment_id, [])
            local_dicts: Dict[str, np.ndarray] = {}
            merged, total = merge_pages_to_arrays(
                pages, node.symbols, node.types_, local_dicts
            )
            for s, t in node.types_:
                if t.is_dictionary and s not in local_dicts:
                    local_dicts[s] = np.array([], dtype=object)
            dicts.update(local_dicts)
            scans[id(node)] = merged
            counts[id(node)] = total
            from .local import dict_fingerprint

            fp = dict_fingerprint(local_dicts, list(local_dicts))
            self._scan_dictfp[id(node)] = fp
            if cache is not None:
                nbytes = sum(
                    int(v.nbytes) + (int(ok.nbytes) if ok is not None else 0)
                    for v, ok in merged.values()
                )
                cache.put(
                    key,
                    {"merged": dict(merged), "dicts": local_dicts,
                     "total": total, "dev": {}, "dictfp": fp},
                    nbytes,
                )
                self._scan_keys[id(node)] = key
            return
        for s in node.sources:
            self._load_walk(s, scans, dicts, counts)

    def _apply_dynamic_filters(self, node, scan_idx, scans, dicts, counts):
        """Prune loaded scan rows by build-side domains before padding —
        the DynamicFilter-SPI pushdown point (rows never reach HBM tiles)."""
        doms_by_sym = {
            sym: doms
            for (i, sym), doms in self.dynamic_filters.items()
            if i == scan_idx
        }
        if not doms_by_sym:
            return
        arrays = scans[id(node)]
        n = counts[id(node)]
        if n == 0:
            return
        from .local import _LazyDeviceLane

        if any(
            isinstance(v, _LazyDeviceLane) for v, _ok in arrays.values()
        ):
            # device-generated scan: no host arrays to prune — the join
            # itself still drops non-matching rows (dynamic filtering is
            # an optimization, never a correctness requirement)
            return
        keep = np.ones(n, bool)
        for sym, doms in doms_by_sym.items():
            v, ok = arrays[sym]
            m = np.ones(n, bool)
            for d in doms:
                m &= d.keep_mask(v[:n], dicts.get(sym))
            if ok is not None:
                m &= ok[:n]  # NULL keys never match an inner equi-join
            keep &= m
        kept = int(keep.sum())
        if kept == n:
            return
        self.df_rows_pruned += n - kept
        idx = np.nonzero(keep)[0]
        for sym, (v, ok) in arrays.items():
            arrays[sym] = (
                v[:n][idx],
                None if ok is None else ok[:n][idx],
            )
        counts[id(node)] = kept
