"""Direct exchange client: pull serialized pages from upstream tasks.

Reference parity: operator/DirectExchangeClient.java:56 (addLocation:154,
pollPage:221) and HttpPageBufferClient.java:98 — async long-poll GET of
``/v1/task/{id}/results/{bufferId}/{token}``, token-acknowledged, with
upstream failure propagation.  Here the pull loop is synchronous per source
with concurrent sources fetched on a small thread pool (the sliding-window
prefetch collapses to "fetch all, fragments are monolithic XLA programs").
"""
from __future__ import annotations

import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List

from ..page import Page
from ..serde import deserialize_page


class RemoteTaskError(RuntimeError):
    pass


class ExchangeTimeout(RuntimeError):
    pass


CREATE_WAIT = 30.0  # max time to wait for an upstream task to appear


def _fetch_buffer(uri: str, task: str, buffer: int, timeout: float) -> List[Page]:
    """Poll one upstream (task, buffer) until complete; returns its pages."""
    pages: List[Page] = []
    token = 0
    seen_task = False
    deadline = time.time() + timeout
    create_deadline = time.time() + CREATE_WAIT
    while True:
        url = f"{uri}/v1/task/{task}/results/{buffer}/{token}"
        try:
            with urllib.request.urlopen(url, timeout=10.0) as resp:
                seen_task = True
                state = resp.headers.get("X-Task-State", "RUNNING")
                if resp.status == 200:
                    body = resp.read()
                    if body:
                        pages.append(deserialize_page(body))
                    if resp.headers.get("X-Buffer-Complete") == "true":
                        return pages
                    token = int(resp.headers.get("X-Next-Token", token + 1))
                    continue
                # 204: not ready yet
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise RemoteTaskError(
                    f"upstream task {task} failed: "
                    f"{e.read().decode(errors='replace')}"
                )
            if e.code != 404:
                raise
            if seen_task:
                # the task existed and is now gone: the query was aborted
                # and the task deleted — stop polling immediately
                raise RemoteTaskError(f"upstream task {task} was deleted")
            if time.time() > create_deadline:
                raise RemoteTaskError(
                    f"upstream task {task} never appeared on {uri}"
                )
            # 404 before first contact: task not created yet — keep polling
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise RemoteTaskError(f"upstream worker {uri} unreachable: {e}")
        if time.time() > deadline:
            raise ExchangeTimeout(f"exchange timeout on {url}")
        time.sleep(0.02)


class ExchangeClient:
    """Fetches all pages for a task's remote sources."""

    def __init__(self, timeout: float = 300.0, concurrency: int = 8):
        self.timeout = timeout
        self.concurrency = concurrency

    def fetch_sources(
        self, sources: Dict[int, List[dict]]
    ) -> Dict[int, List[Page]]:
        """sources: fragment_id -> list of locations, each either a live
        task buffer {uri, task, buffer} (pipelined mode) or a committed
        spool file {path} (fault-tolerant mode)."""
        out: Dict[int, List[Page]] = {}
        flat = [
            (fid, loc) for fid, locs in sources.items() for loc in locs
        ]
        if not flat:
            return out

        def fetch(loc: dict) -> List[Page]:
            if "path" in loc:
                from ..exchange.filesystem import read_spool_pages

                return read_spool_pages(loc["path"])
            return _fetch_buffer(
                loc["uri"], loc["task"], int(loc["buffer"]), self.timeout
            )

        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            futures = [(fid, pool.submit(fetch, loc)) for fid, loc in flat]
            for fid, fut in futures:
                out.setdefault(fid, []).extend(fut.result())
        return out
