"""Direct exchange client: pull serialized pages from upstream tasks.

Reference parity: operator/DirectExchangeClient.java:56 (addLocation:154,
pollPage:221) and HttpPageBufferClient.java:98 — async long-poll GET of
``/v1/task/{id}/results/{bufferId}/{token}``, token-acknowledged, with
upstream failure propagation.  Here the pull loop is synchronous per source
with concurrent sources fetched on a small thread pool (the sliding-window
prefetch collapses to "fetch all, fragments are monolithic XLA programs").

Transient-failure handling mirrors HttpPageBufferClient's backoff
(exchange.max-error-duration role): token-addressed result fetches are
idempotent — re-GETting the same /{token} re-reads the same frame — so a
dropped connection or refused socket retries with exponential backoff +
jitter inside a bounded budget before the upstream is declared dead.
410/deleted-task semantics are NOT retried: those are authoritative.
"""
from __future__ import annotations

import random
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..page import Page
from ..serde import deserialize_page
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER


class RemoteTaskError(RuntimeError):
    pass


class RemoteHostGoneError(RemoteTaskError):
    """Connection REFUSED by an upstream worker: its process is gone
    (kill -9, OOM-kill, a DRAINED node terminated) — a refused socket is
    authoritative in a way a timeout or reset is not, because the task's
    host was reachable when the location was handed out.  Raised after
    one quick re-probe instead of the full transient backoff so FTE
    reassignment (or retry_policy=query's whole-query retry) starts
    immediately instead of spinning against a dead URI."""

    def __init__(self, uri: str, task: str, detail):
        super().__init__(
            f"REMOTE_HOST_GONE: worker {uri} refused connection while "
            f"fetching task {task}: {detail}"
        )
        self.uri = uri


def _connection_refused(exc: BaseException) -> bool:
    return isinstance(exc, ConnectionRefusedError) or isinstance(
        getattr(exc, "reason", None), ConnectionRefusedError
    )


CREATE_WAIT = 30.0  # max time to wait for an upstream task to appear
RETRY_ATTEMPTS = 3  # transient-error tries per contiguous failure streak
RETRY_BUDGET_S = 5.0  # wall-clock budget for one failure streak
RETRY_BASE_S = 0.1  # first backoff; doubles per consecutive failure
REFUSED_FAST_TRIES = 2  # refused connections before the host is GONE


def _fetch_buffer(
    uri: str,
    task: str,
    buffer: int,
    timeout: float,
    retries: int = RETRY_ATTEMPTS,
    retry_budget_s: float = RETRY_BUDGET_S,
    injector=None,
    cross_host: bool = False,
) -> List[Page]:
    """Poll one upstream (task, buffer) until complete; returns its pages."""
    pages: List[Page] = []
    token = 0
    seen_task = False
    deadline = time.time() + timeout
    create_deadline = time.time() + CREATE_WAIT
    transient = 0  # consecutive transient failures in the current streak
    refused = 0  # consecutive connection-refused (dead-host fast path)
    streak_deadline = 0.0
    fetch_total = REGISTRY.counter(
        "trino_tpu_exchange_fetch_total", "Exchange buffer-fetch HTTP requests"
    )
    retry_total = REGISTRY.counter(
        "trino_tpu_exchange_retry_total", "Exchange fetch backoff retries"
    )
    fetch_bytes = REGISTRY.counter(
        "trino_tpu_exchange_fetch_bytes", "Serialized page bytes pulled over exchange"
    )
    # genuinely-cross-host series: only fetches whose target is another
    # process's URI — the multi-host acceptance tests assert network
    # exchange on these, never inferring it from totals that local
    # (same-process) fetches also bump
    x_total = x_bytes = None
    if cross_host:
        x_total = REGISTRY.counter(
            "trino_tpu_exchange_cross_host_fetch_total",
            "Exchange fetches targeting a different host process",
        )
        x_bytes = REGISTRY.counter(
            "trino_tpu_exchange_cross_host_fetch_bytes",
            "Serialized page bytes pulled from other host processes",
        )
    while True:
        url = f"{uri}/v1/task/{task}/results/{buffer}/{token}"
        try:
            if injector is not None and injector.fires(
                "exchange_fetch", key=url
            ):
                raise urllib.error.URLError(
                    "injected transient exchange failure"
                )
            fetch_total.inc()
            if x_total is not None:
                x_total.inc()
            with urllib.request.urlopen(url, timeout=10.0) as resp:
                seen_task = True
                transient = 0
                refused = 0
                state = resp.headers.get("X-Task-State", "RUNNING")
                if resp.status == 200:
                    body = resp.read()
                    if body:
                        fetch_bytes.inc(len(body))
                        if x_bytes is not None:
                            x_bytes.inc(len(body))
                        pages.append(deserialize_page(body))
                    if resp.headers.get("X-Buffer-Complete") == "true":
                        return pages
                    token = int(resp.headers.get("X-Next-Token", token + 1))
                    continue
                # 204: not ready yet
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise RemoteTaskError(
                    f"upstream task {task} failed: "
                    f"{e.read().decode(errors='replace')}"
                )
            if e.code != 404:
                raise
            if seen_task:
                # the task existed and is now gone: the query was aborted
                # and the task deleted — stop polling immediately
                raise RemoteTaskError(f"upstream task {task} was deleted")
            if time.time() > create_deadline:
                raise RemoteTaskError(
                    f"upstream task {task} never appeared on {uri}"
                )
            # 404 before first contact: task not created yet — keep polling
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            if _connection_refused(e):
                # dead-host fast path: one immediate re-probe absorbs an
                # accept-queue blip, then the host is declared gone —
                # the exponential backoff is reserved for errors a live
                # host can produce (timeout, reset, half-open close)
                refused += 1
                if refused >= REFUSED_FAST_TRIES:
                    REGISTRY.counter(
                        "trino_tpu_exchange_host_gone_total",
                        "Exchange fetches failed fast on a refused "
                        "(dead-host) connection",
                    ).inc()
                    raise RemoteHostGoneError(uri, task, e)
                time.sleep(RETRY_BASE_S)
                continue
            refused = 0
            transient += 1
            if transient == 1:
                streak_deadline = time.time() + retry_budget_s
            if transient > retries or time.time() > min(
                deadline, streak_deadline
            ):
                raise RemoteTaskError(
                    f"upstream worker {uri} unreachable after "
                    f"{transient} tries: {e}"
                )
            retry_total.inc()
            backoff = RETRY_BASE_S * (2 ** (transient - 1))
            time.sleep(min(backoff * (1.0 + random.random()), 2.0))
            continue
        if time.time() > deadline:
            raise ExchangeTimeout(f"exchange timeout on {url}")
        time.sleep(0.02)


class ExchangeClient:
    """Fetches all pages for a task's remote sources."""

    def __init__(
        self,
        timeout: float = 300.0,
        concurrency: int = 8,
        retries: Optional[int] = None,
        retry_budget_s: Optional[float] = None,
        fault_injector=None,
        traceparent: Optional[str] = None,
        own_uri: Optional[str] = None,
    ):
        self.timeout = timeout
        self.concurrency = concurrency
        self.retries = RETRY_ATTEMPTS if retries is None else int(retries)
        self.retry_budget_s = (
            RETRY_BUDGET_S if retry_budget_s is None else float(retry_budget_s)
        )
        self.fault_injector = fault_injector
        # this worker's own base URI: fetches targeting any OTHER uri are
        # cross-host network exchanges and get their own metric series
        self.own_uri = (own_uri or "").rstrip("/")
        # W3C trace context of the hosting task: fetch spans run on pool
        # threads with empty span stacks, so the link must be explicit
        self.traceparent = traceparent
        # OperatorStats blocked-on-exchange: wall of the last
        # fetch_sources call (the worker attributes it to the task's
        # RemoteSource frames)
        self.last_fetch_wall_s = 0.0

    def fetch_sources(
        self, sources: Dict[int, List[dict]]
    ) -> Dict[int, List[Page]]:
        """sources: fragment_id -> list of locations, each either a live
        task buffer {uri, task, buffer} (pipelined mode) or a committed
        spool file {path} (fault-tolerant mode).  Spool corruption
        propagates as SpoolCorruptionError so the hosting task FAILS and
        the FTE retry loop owns the recovery."""
        out: Dict[int, List[Page]] = {}
        self.last_fetch_wall_s = 0.0
        flat = [
            (fid, loc) for fid, locs in sources.items() for loc in locs
        ]
        if not flat:
            return out
        fetch_t0 = time.time()

        fetch_seconds = REGISTRY.histogram(
            "trino_tpu_exchange_fetch_seconds", "Wall time of one exchange source fetch"
        )

        def fetch(loc: dict) -> List[Page]:
            if "path" in loc:
                from ..exchange.filesystem import (
                    SpoolCorruptionError,
                    read_spool_pages,
                )

                with TRACER.span(
                    "spool_read", traceparent=self.traceparent, path=loc["path"]
                ):
                    if self.fault_injector is not None and (
                        self.fault_injector.fires("spool_read", key=loc["path"])
                    ):
                        raise SpoolCorruptionError(
                            loc["path"], "injected spool read fault"
                        )
                    return read_spool_pages(loc["path"])
            start = time.time()
            with TRACER.span(
                "exchange_fetch",
                traceparent=self.traceparent,
                uri=loc["uri"],
                task=loc["task"],
            ):
                pages = _fetch_buffer(
                    loc["uri"], loc["task"], int(loc["buffer"]), self.timeout,
                    self.retries, self.retry_budget_s, self.fault_injector,
                    cross_host=bool(
                        self.own_uri
                        and loc["uri"].rstrip("/") != self.own_uri
                    ),
                )
            fetch_seconds.observe(time.time() - start)
            return pages

        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            futures = [(fid, pool.submit(fetch, loc)) for fid, loc in flat]
            for fid, fut in futures:
                out.setdefault(fid, []).extend(fut.result())
        self.last_fetch_wall_s = time.time() - fetch_t0
        return out
