"""Local execution: logical plan -> one jitted XLA program per fragment.

Reference parity: sql/planner/LocalExecutionPlanner.java:393 (fragment ->
OperatorFactory chain) + operator/Driver.java:66 (the page-passing loop).

TPU-first redesign: instead of a pull/push operator loop moving 8192-row
pages between codegen'd operators, the whole fragment is *one traced jax
function* over padded column arrays — XLA fuses scan->filter->project->
aggregate into a single kernel schedule (the PageProcessor, GroupByHash and
accumulator codegen collapse into the compiler).  The host side only:
  1. generates/loads splits (numpy), pads to static tile capacities,
  2. invokes the compiled program,
  3. re-runs with a larger group capacity if the true group count
     overflowed (recompile-on-bucket-change, replacing FlatHash rehash),
  4. compacts the final selection mask and decodes dictionaries.

Batch representation inside the trace: dict[symbol -> (values, valid)] plus
a boolean selection mask 'sel' (the SelectedPositions analog) and an
ordering guarantee flag.  Aggregate group outputs use their group-id order;
Sort/TopN emit compacted, ordered prefixes.
"""
from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import shapes
from .. import types as T
from ..catalog import CatalogManager, Metadata
from ..expr import ir
from ..expr.lower import LoweringContext, compile_expr
from ..ops import aggregation as agg_ops
from ..ops import join as join_ops
from ..ops import sort as sort_ops
from ..obs import compile_observatory as _compile_obs
from ..obs.bandwidth import BandwidthLedger
from ..ops import tree_nbytes
from ..ops import window as window_ops
from ..page import Column, Page, pad_to
from ..plan import nodes as P
from ..runtime import Breadcrumb, DeviceFaultError, default_supervisor
from ..spi import Split
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER

DEFAULT_GROUP_CAPACITY = 4096


def _shape_summary(tree, limit: int = 24) -> dict:
    """Compact ``lane -> dtype[shape]`` summary of a dispatch's inputs,
    recorded in the crash-forensics breadcrumb before the dispatch."""
    out: dict = {}

    def add(name, v):
        if len(out) < limit and hasattr(v, "shape") and hasattr(v, "dtype"):
            out[name] = "%s%s" % (v.dtype, tuple(v.shape))

    for k, lanes in (tree or {}).items():
        if isinstance(lanes, dict):
            for s, v in lanes.items():
                if isinstance(v, tuple):
                    for i, vi in enumerate(v):
                        add("%s.%s.%d" % (k, s, i), vi)
                else:
                    add("%s.%s" % (k, s), v)
        else:
            add(str(k), lanes)
    return out


class DeviceScanCache:
    """Cross-query scan cache: host merged arrays + padded device lanes.

    The reference streams pages from disk/page-cache every query; here the
    analog of a warm OS page cache is warm HBM — repeated scans of an
    unchanged (connector-versioned) table reuse uploaded device arrays,
    which matters doubly when the accelerator sits behind a network tunnel.
    Entries evict in insertion order once the byte budget is exceeded."""

    def __init__(self, max_bytes: int = 6 << 30):
        self.max_bytes = max_bytes
        self.entries: Dict[tuple, dict] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0

    def get(self, key: tuple, record: bool = True):
        """record=False for secondary lookups of an already-counted entry
        (the device-lane rebind path re-reads what _load_one_scan found)."""
        entry = self.entries.get(key)
        if record:
            if entry is not None:
                self.hits += 1
            else:
                self.misses += 1
        return entry

    def put(self, key: tuple, entry: dict, nbytes: int):
        while self.bytes + nbytes > self.max_bytes and self.entries:
            oldest = next(iter(self.entries))
            self.bytes -= self.entries.pop(oldest).get("nbytes", 0)
            self.evictions += 1
        entry["nbytes"] = nbytes
        self.entries[key] = entry
        self.bytes += nbytes
        self.puts += 1

    def drop_all(self) -> int:
        """Evict everything; returns bytes freed.  Registered with the
        LocalMemoryManager as a revocable resource — warm-HBM cache is
        the first thing to go under memory pressure."""
        freed = self.bytes
        self.evictions += len(self.entries)
        self.entries.clear()
        self.bytes = 0
        return freed

    def stats(self) -> Dict[str, int]:
        return {
            "name": "scan_cache",
            "hits": self.hits,
            "misses": self.misses,
            "puts": self.puts,
            "evictions": self.evictions,
            "entries": len(self.entries),
            "bytes": self.bytes,
            "max_bytes": self.max_bytes,
            "heals": 0,
            "invalidations": 0,
        }


class ExecutionError(RuntimeError):
    pass


@dataclasses.dataclass
class Batch:
    lanes: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]
    sel: jnp.ndarray
    ordered: bool = False  # rows already compacted+ordered (sort output)
    replicated: bool = False  # identical on every mesh device (mesh exec)


def _single_row_plan(n: P.PlanNode) -> bool:
    """Does this plan emit at most one row, statically?  (Global
    aggregates and LIMIT<=1, through projections/filters — filters may
    drop the row, which cross-join semantics must and do preserve.)"""
    if isinstance(n, P.Aggregate):
        return not n.keys and n.step in ("single", "final")
    if isinstance(n, P.Limit):
        return n.count <= 1 or _single_row_plan(n.sources[0])
    if isinstance(n, P.Values):
        return len(n.rows) <= 1
    if isinstance(n, (P.Project, P.Filter)):
        return _single_row_plan(n.sources[0])
    return False


def _contains(plan: P.PlanNode, node_type, pred=None) -> bool:
    if isinstance(plan, node_type) and (pred is None or pred(plan)):
        return True
    return any(_contains(s, node_type, pred) for s in plan.sources)


def _contains_host_aggs(plan: P.PlanNode) -> bool:
    """Aggregates building per-group host dictionaries (array_agg /
    map_agg / listagg) run eagerly, like UNNEST."""
    from ..ops.aggregation import HOST_STAGED_KINDS

    return _contains(
        plan, P.Aggregate,
        lambda n: any(a.kind in HOST_STAGED_KINDS for a in n.aggs),
    )

def _pad_capacity(n: int) -> int:
    """Static tile capacity: next multiple of 128 (TPU lane width).

    Back-compat alias of :func:`shapes.lane_align`; executor paths
    quantize through ``self.ladder`` (the bucketed-batch ABI) instead,
    so arbitrary row counts collapse onto a bounded set of shapes.
    """
    return shapes.lane_align(n)


class _LazyDeviceLane:
    """Placeholder for a scan column that will be GENERATED on-device
    (no host array exists).  Carries the estimated byte size so memory
    accounting sees the eventual HBM footprint."""

    __slots__ = ("nbytes",)

    def __init__(self, nbytes: int):
        self.nbytes = int(nbytes)


def merge_pages_to_arrays(pages, symbols, types, dicts):
    """Concatenate pages column-wise into host arrays; varchar dictionaries
    from different producers (splits / exchange tasks) are merged with codes
    remapped (the cross-task DictionaryBlock unification).  Fast path: when
    every page shares one dictionary (the common same-connector case) codes
    pass through untouched."""
    tmap = dict(types)
    merged = {}
    total = sum(p.count for p in pages)
    for sym in symbols:
        t = tmap[sym]
        vals_parts: List[np.ndarray] = []
        ok_parts: List[np.ndarray] = []
        live = [p for p in pages if p.count > 0]
        if t.is_dictionary:
            page_dicts = []
            for p in live:
                d = p.by_name(sym).dictionary
                if d is None:
                    raise ExecutionError(f"varchar column {sym} without dict")
                page_dicts.append(d)
            shared = True
            for d in page_dicts[1:]:
                if d is not page_dicts[0] and not np.array_equal(
                    page_dicts[0], d
                ):
                    shared = False
                    break
            if shared:
                dicts[sym] = (
                    page_dicts[0]
                    if page_dicts
                    else np.array([], dtype=object)
                )
                for p in live:
                    col = p.by_name(sym)
                    vals_parts.append(
                        np.asarray(col.values)[: p.count].astype(np.int32)
                    )
                    ok_parts.append(_valid_of(col, p.count))
            else:
                index: Dict[str, int] = {}
                entries: List[str] = []
                for p, d in zip(live, page_dicts):
                    col = p.by_name(sym)
                    codes = np.asarray(col.values)[: p.count]
                    remap = np.empty(len(d), dtype=np.int32)
                    for i, s in enumerate(d):
                        s = str(s)
                        if s not in index:
                            index[s] = len(entries)
                            entries.append(s)
                        remap[i] = index[s]
                    safe = np.clip(codes, 0, max(len(d) - 1, 0))
                    vals_parts.append(
                        np.where(codes >= 0, remap[safe], -1).astype(np.int32)
                    )
                    ok_parts.append(_valid_of(col, p.count))
                dicts[sym] = np.array(entries, dtype=object)
        else:
            for p in live:
                col = p.by_name(sym)
                vals_parts.append(np.asarray(col.values)[: p.count])
                ok_parts.append(_valid_of(col, p.count))
        if vals_parts:
            vals = np.concatenate(vals_parts)
            ok = np.concatenate(ok_parts)
        else:
            vals = np.zeros(0, dtype=t.np_dtype)
            ok = np.zeros(0, dtype=bool)
        merged[sym] = (vals, None if ok.all() else ok)
    return merged, total


def dict_fingerprint(dicts: Dict[str, np.ndarray], symbols) -> int:
    """Exact content hash of the dictionaries for these symbols (dict
    codes are baked into traced programs as constants; identical
    fingerprints are required to share a compiled executable).  blake2b,
    not hash(): the fingerprint flows into compile-cache keys whose
    persistent tier must be stable across processes, and str hashing is
    salted per process."""
    h = hashlib.blake2b(digest_size=8)
    for s in sorted(symbols):
        d = dicts.get(s)
        if d is None:
            continue
        h.update(f"{s}\x1f{len(d)}\x1f".encode())
        for x in d:
            h.update(str(x).encode() + b"\x00")
    return int.from_bytes(h.digest(), "little")


def _is_null_expr(e: ir.Expr) -> bool:
    while isinstance(e, ir.Cast):
        e = e.term
    if isinstance(e, ir.Constant) and e.value is None:
        return True
    # a column of UNKNOWN type can only hold NULLs (NULL-literal columns)
    return e.type.name == "unknown"


def _valid_of(col: Column, n: int) -> np.ndarray:
    return (
        np.ones(n, bool)
        if col.validity is None
        else np.asarray(col.validity)[:n]
    )


class LocalExecutor:
    """Executes an optimized logical plan on the local device(s)."""

    trace_ctx_cls: type  # bound after _TraceCtx definition

    def __init__(self, catalogs: CatalogManager, config: Optional[dict] = None):
        self.catalogs = catalogs
        self.metadata = Metadata(catalogs)
        self.config = config or {}
        self.query_id = str(self.config.get("query_id", "query"))
        # the bucketed-batch ABI: every padded capacity in this executor
        # quantizes through one ladder, so split sizes collapse onto a
        # bounded set of compiled shapes per kernel family.  The session
        # resolves the ladder once and shares the object via config;
        # bare executors (tests) resolve from the spec/file props here.
        self.ladder = shapes.resolve_ladder(self.config)
        # scan-node id -> capacity actually dispatched (ladder rung after
        # scan_cap_override): kernel profile + bandwidth ledger report
        # padded bytes from these, never from recomputed lane alignment
        self._scan_caps: Dict[int, int] = {}
        self.scan_bytes = 0
        # EXPLAIN ANALYZE: id(plan node) -> {rows, bytes, wall_s,
        # device_wall_s, calls} (OperatorStats analog, filled when
        # collect_node_stats is set; obs/opstats.frames_from_plan turns
        # these into the wire-shape timeline frames)
        self.node_stats: Dict[int, dict] = {}
        # query-level blocked time (OperatorStats blocked walls): waiting
        # on a memory reservation / on exchange pages.  Attributed at the
        # task rollup; exchange wait is set by the worker around
        # ExchangeClient.fetch_sources
        self.blocked_memory_s = 0.0
        self.blocked_exchange_s = 0.0
        # per-query TPU kernel profile: one record per compiled (or eager)
        # fragment program — compile wall, recompiles, padded-vs-actual
        # rows, host<->device byte estimates.  Surfaced via EXPLAIN
        # ANALYZE, /v1/query/{id}/profile, the web UI, and bench output.
        self.kernel_profile: Dict[str, object] = {"kernels": [], "summary": {}}
        # scan-node id -> DeviceScanCache key (None when uncacheable)
        self._scan_keys: Dict[int, tuple] = {}
        self._scan_nodes: Dict[int, P.TableScan] = {}
        # scan-node id -> dictionary-content fingerprint (jit-key part)
        self._scan_dictfp: Dict[int, int] = {}
        # scan-node id -> on-device generation spec (connector-provided;
        # lanes materialize in HBM, no host arrays exist)
        self._devgen: Dict[int, dict] = {}
        # supervised dispatch boundary: session/worker-owned supervisor
        # when wired, process default otherwise (bare executors in tests)
        self.supervisor = self.config.get("device_supervisor") \
            or default_supervisor()
        # HBM bandwidth ledger (obs/bandwidth.py): per-kernel bytes/wall
        # accounting behind the bandwidth_ledger session property (EXPLAIN
        # ANALYZE forces it on) — the block_until_ready bracketing
        # serializes the async dispatch pipeline, so it stays opt-in
        self.bandwidth_ledger = (
            BandwidthLedger()
            if self.config.get("bandwidth_ledger") else None
        )
        self.device_bytes = 0
        # True while re-executing on the CPU backend after a device fault:
        # dispatches bypass supervision (the watchdog side thread would
        # escape the thread-local jax.default_device context).  Inherited
        # through the config so spill/streaming sub-executors created
        # mid-fallback stay on the CPU path too.
        self._device_fallback = bool(self.config.get("_in_device_fallback"))

    # ------------------------------------------------------------------
    def execute(self, plan: P.PlanNode) -> Page:
        assert isinstance(plan, P.Output)
        if isinstance(plan.source, P.TableWriter):
            return self._execute_write(plan.source)
        sup = self.supervisor
        if not self._device_fallback:
            sup.maybe_probe()
            if not sup.healthy():
                # device already out: degrade up front (or refuse with
                # the structured error when fallback is disabled)
                bc = Breadcrumb(
                    "pre-dispatch", query_id=self.query_id,
                    task_id=str(self.config.get("task_id") or ""),
                    mode="gate",
                )
                fault = DeviceFaultError(
                    "device_" + sup.device_state().lower(), bc
                )
                if not self._cpu_fallback_enabled():
                    raise fault
                return self._run_cpu_fallback(plan, fault)
        try:
            return self._execute_inner(plan)
        except DeviceFaultError:
            if self._device_fallback or not self._cpu_fallback_enabled():
                raise
            return self._run_cpu_fallback(plan, None)

    def _cpu_fallback_enabled(self) -> bool:
        v = self.config.get("device_cpu_fallback", True)
        if isinstance(v, str):
            v = v.strip().lower() not in ("false", "0", "no", "off", "")
        return bool(v)

    def _run_cpu_fallback(self, plan: P.PlanNode, fault) -> Page:
        """Degraded mode: re-run the whole fragment eagerly on the CPU
        backend.  The faulted device's compiled programs and cached
        device arrays are unusable, so jit and the scan cache are
        disabled for the retry; the supervisor keeps advertising the
        sick device so schedulers route around this node meanwhile."""
        sup = self.supervisor
        sup.note_fallback_attempt(query_id=self.query_id)
        orig_config = self.config
        cfg = dict(orig_config)
        cfg["jit_fragments"] = False
        cfg["scan_cache"] = None
        cfg["device_generation"] = False
        cfg["_in_device_fallback"] = True
        self.config = cfg
        self._preloaded = None
        self._device_fallback = True
        # compiled devgen generators are bound to the faulted device;
        # drop them so a recovered device recompiles fresh executables
        from ..connectors import tpch_device

        tpch_device.clear_jit_cache()
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                page = self.execute(plan)
            sup.note_fallback_completed()
            return page
        finally:
            self.config = orig_config
            self._device_fallback = False

    # -- supervised dispatch helpers -----------------------------------
    def _dispatch_crumb(self, kernel: str, mode: str, tree=None) -> Breadcrumb:
        bc = Breadcrumb(
            kernel,
            query_id=self.query_id,
            task_id=str(self.config.get("task_id") or ""),
            mode=mode,
            shapes=_shape_summary(tree),
            hbm_reserved_bytes=getattr(self, "device_bytes", 0),
        )
        # forensics ride the per-query kernel profile too (EXPLAIN
        # ANALYZE / /v1/query/{id}/profile / bench artifacts)
        self.kernel_profile["last_breadcrumb"] = bc.to_dict()
        return bc

    def _dispatch(self, thunk, bc: Breadcrumb):
        if self._device_fallback:
            return thunk()
        return self.supervisor.dispatch(thunk, bc)

    def _device_get(self, objs, bc: Breadcrumb):
        if self._device_fallback:
            return jax.device_get(objs)  # dispatch-guard: ok
        return self.supervisor.device_get(objs, bc)

    def _megakernel_mode(self) -> str:
        """Effective fused scan->filter->aggregate mode: 'on'/'off'.
        Session prop `megakernels`: 'auto' fuses only where the pallas
        TPU path is live (interpret-mode fusion on CPU would just slow
        eager tests down); 'on' forces fusion (interpret mode off-TPU —
        how the parity tests drive the fused path); 'off' disables."""
        v = str(self.config.get("megakernels", "auto") or "auto").lower()
        if v not in ("auto", "on", "off"):
            v = "auto"
        if v == "auto":
            from ..ops import pallas_kernels

            if self._device_fallback or not pallas_kernels.enabled():
                return "off"
            return "on"
        return v

    # -- HBM bandwidth ledger ------------------------------------------
    def _ledger_input_bytes(self, scans) -> int:
        """Padded host bytes fed to the program: the scan (and merged
        exchange) arrays scaled to the ladder rung each scan actually
        dispatched at (recorded by `_device_lanes`), so the ledger's
        GB/s agrees with the buffers XLA really moved — and with the
        padding ratios the observatory census reports."""
        total = 0
        for nid, arrays in scans.items():
            rows = max(
                (int(getattr(v, "shape", (0,))[0] or 0)
                 for v, _ok in arrays.values() if hasattr(v, "shape")),
                default=0,
            )
            cap = self._scan_caps.get(nid)
            scale = (int(cap) / rows) if (cap and rows) else 1.0
            for v, ok in arrays.values():
                nb = int(getattr(v, "nbytes", 0) or 0)
                if ok is not None:
                    nb += int(getattr(ok, "nbytes", 0) or 0)
                total += int(nb * scale)
        return total

    def _ledger_bracket(self, out, digest, mode, plan, scans, start):
        """Close one ledger observation: drain the async dispatch
        pipeline (supervised, so a wedge/loss during the sync still
        breadcrumbs and flight-records) and fold bytes over the wall."""
        led = self.bandwidth_ledger
        if led is None:
            return
        bc = self._dispatch_crumb(digest, "sync")
        self._dispatch(
            lambda: jax.block_until_ready(out), bc  # dispatch-guard: ok
        )
        wall = time.perf_counter() - start
        from . import streaming

        try:
            scan_est = streaming.estimate_plan_scan_bytes(self, plan)
            inter = int(max(
                0.0,
                streaming.estimate_program_bytes(self, plan) - scan_est,
            ))
        except Exception:
            # estimators reject exotic plans (e.g. UNNEST) — the ledger
            # then reports input+output only rather than nothing
            inter = 0
        led.record(
            digest,
            mode,
            input_bytes=self._ledger_input_bytes(scans),
            output_bytes=tree_nbytes(out),
            intermediate_bytes=inter,
            wall_s=wall,
            task_id=str(self.config.get("task_id") or ""),
        )

    # ------------------------------------------------------------------
    def _execute_inner(self, plan: P.PlanNode) -> Page:
        # out-of-core path: when the estimated scan working set exceeds the
        # memory limit and the plan allows it, aggregate in split batches
        # (MemoryRevokingScheduler -> spill, host RAM as the spill tier)
        limit = self.config.get("memory_limit_bytes")
        if limit and self.config.get("spill_enabled", True):
            from . import spill, streaming

            # DISTINCT aggregation first: the streaming fragmenter keeps
            # a distinct Aggregate single-step behind one hash exchange,
            # which locally gathers every input row into one in-memory
            # fragment — the spill rewrite partitions host-side instead
            sp = spill.plan_distinct_spill(self, plan, int(limit))
            if sp is not None:
                return spill.execute_spilled_distinct(self, plan, *sp)
            # streaming (fragment-tiled) execution next: the general
            # bounded-working-set path; shape-matched spill rewrites
            # remain for plans the fragmenter cannot tile
            frags = streaming.plan_streaming(self, plan, int(limit))
            if frags is not None:
                return streaming.execute_streaming(
                    self, plan, frags, int(limit)
                )
            sp = spill.plan_spill(self, plan, int(limit))
            if sp is not None:
                return spill.execute_spilled_aggregation(self, plan, *sp)
            sp = spill.plan_join_spill(self, plan, int(limit))
            if sp is not None:
                return spill.execute_spilled_join(self, plan, *sp)
            sp = spill.plan_sort_spill(self, plan, int(limit))
            if sp is not None:
                return spill.execute_spilled_sort(self, plan, *sp)
            sp = spill.plan_window_spill(self, plan, int(limit))
            if sp is not None:
                return spill.execute_spilled_window(self, plan, *sp)
        # 1. host side: load scans, collect dictionaries — or adopt the
        # arrays a streaming prefetcher loaded on a background thread
        # while the previous tile computed on-device (double buffering)
        pre = getattr(self, "_preloaded", None)
        if pre is not None and pre[0] is plan:
            _, scans, dicts, counts = pre
            self._preloaded = None
        else:
            scans = {}
            dicts = {}
            counts = {}
            self._load_scans(plan, scans, dicts, counts)
        self._account_memory(scans, limit)
        pool = self.config.get("memory_pool")
        manager = self.config.get("memory_manager")
        self.device_bytes = 0
        if manager is not None:
            # HBM tier: every kernel is static-shape, so the device
            # working set (padded batches + compiled program) is known
            # before dispatch; a query that would blow HBM is blocked,
            # spilled via revocation, or failed cleanly here instead of
            # kernel-faulting the backend
            from ..memory import QueryKilledError
            from ..utils.memory import ExceededMemoryLimitError
            from .streaming import estimate_program_bytes

            est = int(max(self.scan_bytes,
                          estimate_program_bytes(self, plan)))
            try:
                _blk_t0 = time.perf_counter()
                manager.reserve(
                    self.query_id, est, tier="device",
                    timeout=float(
                        self.config.get("memory_blocked_timeout_s") or 0.0
                    ),
                )
                self.blocked_memory_s += time.perf_counter() - _blk_t0
                self.device_bytes = est
            except ExceededMemoryLimitError as exc:
                manager.free(self.query_id, self.scan_bytes, tier="host")
                self.scan_bytes = 0
                if isinstance(exc, QueryKilledError):
                    raise
                out = self._try_forced_streaming(plan)
                if out is not None:
                    return out
                raise
        try:
            self.dicts = dicts
            self.group_capacity = int(
                self.config.get("group_capacity", DEFAULT_GROUP_CAPACITY)
            )
            self.join_factor = 1
            self.compact_factor = 1
            # join nodes whose build side turned out to hold duplicate (or
            # hash-colliding) keys: re-traced with the expansion kernel
            # (HashBuilderOperator never assumes uniqueness; we learn it)
            self.force_expansion = set()
            # direct-address joins whose domain proof failed at runtime
            # (stale stats): first rung retries the sorted UNIQUE kernel
            # (still exact for a unique key outside its claimed domain);
            # only a genuine duplicate then escalates to expansion
            self.force_no_direct = set()
            self.group_salt = 0
            self.topn_factor = int(
                self.config.get("topn_initial_factor") or 1
            )
            self.force_wide_mul = False
            # start at the last successful capacities for this plan: the
            # overflow ladder re-runs (and on first touch, re-COMPILES) the
            # whole fragment per rung, so remembering the landing spot makes
            # warm repeats single-shot (FlatHash keeps its size the same way)
            hints = self.config.get("capacity_hints")
            hint = hints.get(id(plan)) if hints is not None else None
            if hint is not None:
                (self.group_capacity, self.join_factor, self.topn_factor,
                 self.force_wide_mul, forced, _) = hint[:6]
                self.compact_factor = hint[6] if len(hint) > 6 else 1
                self.force_no_direct = (
                    set(hint[7]) if len(hint) > 7 else set()
                )
                self.force_expansion = set(forced)
            else:
                est = self._estimate_group_capacity(plan, counts)
                if est is not None:
                    self.group_capacity = max(self.group_capacity, est)

            use_jit = (
                self.config.get("jit_fragments")
                and not self.config.get("collect_node_stats")
                and not _contains(plan, (P.Unnest, P.MatchRecognize))
                and not _contains_host_aggs(plan)
                # unversioned sources (system tables, hive files) may change
                # without shape changes: no safe compiled-fragment reuse
                and all(
                    self._scan_keys.get(nid) is not None
                    for nid in scans
                    if nid in self._scan_nodes
                )
            )
            for attempt in range(7):
                # the observatory classifies attempt>0 compiles as
                # ladder rungs (capacity/fallback re-traces)
                self._ladder_attempt = attempt
                # ONE round trip for all control scalars AND the output
                # lanes (the accelerator may sit behind a high-latency
                # tunnel: each device_get costs an RTT; on the rare
                # retry the prefetched outputs are simply discarded).
                # The axon executable-reuse fault can surface either at
                # dispatch (fn call) or at device_get, so the retry
                # wraps both.
                try:
                    if use_jit:
                        (out_lanes, sel, ordered, checks, dups, colls,
                         wides, sflags) = self._run_jitted(
                            plan, scans, counts
                        )
                    else:
                        eager_start = time.time()
                        ctx = self.trace_ctx_cls(self, scans, counts)
                        bc = self._dispatch_crumb(
                            "eager-%d" % attempt, "eager", scans
                        )
                        self._last_crumb = bc
                        led_t0 = time.perf_counter()
                        out_lanes, sel, ordered, checks = self._dispatch(
                            lambda: self._run(plan, ctx), bc
                        )
                        self._ledger_bracket(
                            (out_lanes, sel), "eager-%d" % attempt,
                            "eager", plan, scans, led_t0,
                        )
                        dups = ctx.dup_checks
                        colls = ctx.collision_checks
                        wides = ctx.lowering.overflow_flags
                        sflags = ctx.sum_overflow
                        # eager mode has no XLA compile step; the trace
                        # wall is the honest analog (and each ladder rung
                        # re-traces, so rungs count as recompiles)
                        ev = _compile_obs.record_compile(
                            kernel="eager-%d" % attempt,
                            family=self._compile_family(plan),
                            mode="eager",
                            shapes=_shape_summary(scans),
                            shape_sig=self._compile_shape_sig(counts),
                            actual_rows=sum(
                                int(c) for c in counts.values()
                            ),
                            padded_rows=self._padded_rows(counts),
                            compile_wall_s=time.time() - eager_start,
                            query_id=self.query_id,
                            task_id=str(
                                self.config.get("task_id") or ""
                            ),
                            node_id=str(
                                self.config.get("node_id") or ""
                            ),
                            ladder_attempt=attempt,
                            scan_rows=[
                                int(c) for c in counts.values()
                            ],
                        )
                        self._record_kernel(
                            "eager-%d" % attempt,
                            compile_s=time.time() - eager_start,
                            cached=False,
                            mode="eager",
                            cause=ev["cause"],
                        )
                    last = getattr(self, "_last_crumb", None)
                    (dup_vals, check_vals, coll_vals, wide_vals,
                     sflag_vals, host_lanes, sel_np) = self._device_get(
                        ([d for _, d in dups],
                         [ng for ng, _, _ in checks],
                         list(colls), list(wides), list(sflags),
                         {s: out_lanes[s] for s in plan.symbols}, sel),
                        self._dispatch_crumb(
                            last.kernel if last else "device_get",
                            "device_get",
                        ),
                    )
                except jax.errors.JaxRuntimeError as e:
                    # axon tunnel executable-reuse fault: the poisoned
                    # object is the CACHED EXECUTABLE (and possibly its
                    # cached device operands), so the remedy is targeted:
                    # evict that one entry and recompile EXACTLY ONCE per
                    # key.  (The old path retried up to three times
                    # "regardless of cache state", re-popping an entry the
                    # first retry already replaced — two extra compiles of
                    # a program that was going to fail identically.)
                    # ONLY for INVALID_ARGUMENT (the observed fault
                    # signature) — OOM/crashes (RESOURCE_EXHAUSTED/
                    # UNAVAILABLE) must surface with their real message.
                    jc = self.config.get("jit_cache")
                    retries = getattr(self, "_jit_fault_retries", 0)
                    msg = str(e)
                    compile_flake = "remote_compile" in msg
                    # a compile-side HBM OOM is PERMANENT: XLA's buffer
                    # assignment proved the monolithic program cannot fit
                    # the chip — but the tunnel surfaces it as the same
                    # HTTP 500 a transient helper crash produces, and the
                    # OOM detail lives only in the tunnel's own log
                    # stream.  So: on an explicit OOM signature, or on
                    # the SECOND consecutive 500 for the same program,
                    # try the streaming tiled fallback; if the plan is
                    # untileable, keep the old backoff-retry resilience
                    # (5 attempts) for genuine helper flakes.
                    compile_oom = (
                        "Ran out of memory" in msg
                        or "permanent error" in msg
                    )
                    if compile_flake and (compile_oom or retries >= 1):
                        stream_page = self._try_forced_streaming(plan)
                        if stream_page is not None:
                            return stream_page
                    key = getattr(self, "_last_jit_key", None)
                    if use_jit and not compile_oom and compile_flake:
                        # remote compile service hiccups (HTTP 500 /
                        # truncated body) are infra flakes, not program
                        # errors — retry them, with a backoff pause so a
                        # briefly overloaded compile helper can recover
                        if retries < 5:
                            import time as _time

                            _time.sleep(3.0 * (retries + 1))
                            self._jit_fault_retries = retries + 1
                            if jc is not None:
                                jc.pop(key, None)
                            continue
                    elif (
                        use_jit
                        and not compile_oom
                        and "INVALID_ARGUMENT" in msg
                    ):
                        poisoned = getattr(self, "_poisoned_jit_keys", None)
                        if poisoned is None:
                            poisoned = self._poisoned_jit_keys = set()
                        if key not in poisoned:
                            # first fault on this key: evict the poisoned
                            # entry, retire cached device buffers, and
                            # recompile once.  A second fault on the same
                            # key means the fresh executable fails too —
                            # a real program error, surface it.
                            poisoned.add(key)
                            if jc is not None:
                                if hasattr(jc, "evict_poisoned"):
                                    jc.evict_poisoned(key)
                                else:
                                    jc.pop(key, None)
                            # cached DEVICE buffers from sibling queries
                            # can be the poisoned operand.  RETIRE them to
                            # a keep-alive graveyard — NOT free them: the
                            # tunnel's async buffer frees are themselves
                            # an observed poison source for later
                            # transfers (bench.py keeps sessions alive
                            # for the same reason) — then re-upload.
                            sc = self.config.get(
                                "scan_cache"
                            ) or getattr(self, "_streaming_cache", None)
                            if sc is not None:
                                # graveyard lives on the SESSION-lived
                                # cache object: a per-query list would be
                                # dropped at query end and free the very
                                # buffers we are keeping alive
                                grave = getattr(sc, "graveyard", None)
                                if grave is None:
                                    grave = sc.graveyard = []
                                for entry in sc.entries.values():
                                    dev = entry.get("dev", {})
                                    if dev:
                                        grave.append(dict(dev))
                                        dev.clear()
                            # the devgen generators keep their own
                            # module-level executable cache
                            # (tpch_device._JIT_CACHE) that this eviction
                            # used to miss: a poisoned generator would be
                            # re-dispatched verbatim on retry (BENCH_r05)
                            from ..connectors import tpch_device

                            tpch_device.clear_jit_cache()
                            continue
                    raise
                fell_back = False
                for (join_node, _), dup in zip(dups, dup_vals):
                    if int(dup) > 0:
                        if join_node is None:
                            # ordinal from a foreign trace did not resolve
                            # in this plan (should be impossible for
                            # fingerprint-matched plans): no node to force
                            raise ExecutionError(
                                "duplicate build keys in unresolvable join"
                            )
                        if (
                            getattr(join_node, "direct_domain", None)
                            is not None
                            and id(join_node) not in self.force_no_direct
                        ):
                            # direct-table domain/dup proof failed: retry
                            # on the sorted unique kernel first
                            self.force_no_direct.add(id(join_node))
                        else:
                            # duplicate (or colliding) build keys:
                            # re-trace with the many-to-many expansion
                            # kernel for this join
                            self.force_expansion.add(id(join_node))
                        fell_back = True
                for cv in coll_vals:
                    if int(cv) > 0:
                        # locator hash collision in grouping: re-run
                        # the fragment under a fresh salt (exactness)
                        self.group_salt += 1
                        fell_back = True
                for wv in wide_vals:
                    if int(wv) > 0 and not self.force_wide_mul:
                        # decimal product/quotient near int64 range:
                        # re-trace with the 128-bit kernels
                        self.force_wide_mul = True
                        fell_back = True
                if fell_back:
                    continue
                over_kinds = set()
                for ngroups, (_, cap, kind) in zip(check_vals, checks):
                    if int(ngroups) > cap:
                        over_kinds.add(kind)
                if not over_kinds:
                    # only a settled attempt may raise: a capacity overflow
                    # or collision retry piles unrelated groups into one
                    # segment, making the shadow flag spurious
                    for sv in sflag_vals:
                        if int(sv) > 0:
                            raise ExecutionError(
                                "sum overflows the bigint accumulator"
                            )
                    break
                if "group" in over_kinds:
                    self.group_capacity *= 8
                if "join" in over_kinds:
                    self.join_factor *= 8
                if "topn" in over_kinds:
                    self.topn_factor *= 8
                if "compact" in over_kinds:
                    # x8 rapidly reaches the input width, where
                    # _maybe_compact becomes a no-op — a bad estimate
                    # costs at most a couple of recompiles, never a loop
                    self.compact_factor *= 8
            else:
                raise ExecutionError("group capacity overflow after retries")

            if hints is not None:
                # the plan reference keeps id(plan) stable (no reuse after gc)
                hints[id(plan)] = (
                    self.group_capacity, self.join_factor,
                    self.topn_factor, self.force_wide_mul,
                    frozenset(self.force_expansion), plan,
                    self.compact_factor,
                    frozenset(self.force_no_direct),
                )
                for k in list(hints)[:-512]:
                    hints.pop(k, None)
            self._finalize_kernel_profile(scans, counts, host_lanes, sel_np)
            return self._materialize_host(plan, host_lanes, sel_np)
        finally:
            if manager is not None:
                manager.free(self.query_id, self.scan_bytes, tier="host")
                if self.device_bytes:
                    manager.free(
                        self.query_id, self.device_bytes, tier="device"
                    )
            elif pool is not None:
                pool.free(self.query_id, self.scan_bytes)

    # ------------------------------------------------------------------
    def _try_forced_streaming(self, plan) -> Optional[Page]:
        """Compile-OOM fallback: re-run the query through the streaming
        tiled executor even though the scan-bytes gate did not trigger —
        XLA already proved the monolithic program exceeds HBM.  Returns
        None when the plan is untileable or streaming itself fails (the
        caller then surfaces the ORIGINAL compile error)."""
        limit = self.config.get("memory_limit_bytes")
        if not (limit and self.config.get("spill_enabled", True)):
            return None
        if not isinstance(plan, P.Output):
            return None
        from . import streaming

        try:
            frags = streaming.plan_streaming(
                self, plan, int(limit), force=True
            )
            if frags is None:
                return None
            out = streaming.execute_streaming(
                self, plan, frags, int(limit)
            )
        except Exception:
            return None
        if out is not None:
            from ..obs import journal

            journal.emit(
                journal.FORCED_STREAMING, query_id=self.query_id,
                severity=journal.WARN,
                fragments=len(frags) if hasattr(frags, "__len__") else 0,
            )
        return out

    # ------------------------------------------------------------------
    def _execute_write(self, w: P.TableWriter) -> Page:
        """INSERT/CTAS/DELETE execution (TableWriterOperator +
        TableFinishOperator collapsed: run the source query, stream the
        result into the connector PageSink, commit at finish())."""
        conn = self.catalogs.get(w.catalog)
        md = conn.metadata()
        if w.create_schema is not None:
            from ..spi import ColumnSchema, TableSchema

            if w.if_not_exists and w.table in md.list_tables():
                return Page(
                    [Column(T.BIGINT, np.zeros(1, dtype=np.int64))], 1,
                    ["rows"],
                )
            md.create_table(
                TableSchema(
                    w.table,
                    tuple(ColumnSchema(c, t) for c, t in w.create_schema),
                )
            )
        before = 0
        if w.report_deleted or w.count_mode == "merge":
            before = int(md.get_table_statistics(w.table).row_count)
        names = list(w.columns)
        if w.count_symbol is not None:
            names.append("__update_count__")
        inner = P.Output(
            w.source, tuple(names), tuple(w.source.output_symbols())
        )
        page = self.execute(inner)
        sink = conn.page_sink_provider().create_sink(
            w.table, list(w.columns), overwrite=w.overwrite
        )
        sink.append(page)
        written = sink.finish()
        if w.count_symbol is not None and w.count_mode == "merge":
            m = np.asarray(
                page.by_name("__update_count__").values
            )[: page.count]
            updates = int((m == 1).sum())
            inserts = int((m == 2).sum())
            deletes = before + inserts - page.count
            result = updates + inserts + deletes
        elif w.count_symbol is not None:
            marker = page.by_name("__update_count__")
            result = int(
                np.asarray(marker.values)[: page.count].sum()
            )
        elif w.report_deleted:
            result = before - written
        else:
            result = written
        return Page(
            [Column(T.BIGINT, np.array([result], dtype=np.int64))], 1,
            ["rows"],
        )

    # ------------------------------------------------------------------
    def _load_scans(self, node: P.PlanNode, scans, dicts, counts):
        if isinstance(node, P.TableScan):
            conn = self.catalogs.get(node.catalog)
            splits = conn.split_manager().get_splits(
                node.table, 1, node.constraint
            )
            self._load_one_scan(node, splits, scans, dicts, counts)
            return
        for s in node.sources:
            self._load_scans(s, scans, dicts, counts)

    def _account_memory(self, scans, limit):
        """Reserve the scan working set against the pool and enforce the
        per-query limit (MemoryPool.reserve + ExceededMemoryLimitException).
        Scan arrays dominate this engine's footprint; kernel temporaries are
        proportional and covered by the limit's headroom."""
        from ..utils.memory import ExceededMemoryLimitError

        scan_total = 0
        for arrays in scans.values():
            for v, ok in arrays.values():
                scan_total += (
                    int(v.nbytes) + (int(ok.nbytes) if ok is not None else 0)
                )
        # fragment tasks also hold the raw exchange pages they fetched —
        # counted toward the node's host reservation below, but NOT
        # against the spillability limit: that limit gates the device
        # working set, and exchange buffers stay in host RAM (a streaming
        # sub-fragment legitimately holds pages + merged copies past it)
        total = scan_total + int(getattr(self, "exchange_bytes", 0))
        self.scan_bytes = total
        if limit and scan_total > int(limit):
            raise ExceededMemoryLimitError(
                f"query exceeded memory limit: scan working set "
                f"{scan_total} > {limit} bytes (and plan is not spillable)"
            )
        manager = self.config.get("memory_manager")
        if manager is not None:
            # revoke -> block -> clean-error semantics (and the seeded
            # `oom` fault site) live in the manager; freed after
            # materialize alongside the device-tier reservation.  Time
            # spent blocked in reserve is OperatorStats blocked-on-memory
            import time as _time

            _blk_t0 = _time.perf_counter()
            manager.reserve(
                self.query_id, total, tier="host",
                timeout=float(
                    self.config.get("memory_blocked_timeout_s") or 0.0
                ),
            )
            self.blocked_memory_s += _time.perf_counter() - _blk_t0
            return
        pool = self.config.get("memory_pool")
        if pool is not None:
            pool.reserve(self.query_id, total)  # freed after materialize

    def _scan_cache_key(self, node: P.TableScan, splits):
        conn = self.catalogs.get(node.catalog)
        if not getattr(conn, "cacheable", False):
            return None
        return (
            node.catalog,
            node.table,
            tuple(c for _, c in node.assignments),
            node.constraint,
            tuple(repr(sp) for sp in splits),
            conn.data_version(node.table),
        )

    def _load_one_scan(self, node: P.TableScan, splits, scans, dicts, counts):
        """Load the given splits of one scan into host arrays (shared by
        local execution — all splits — and per-task fragment execution —
        the assigned subset, SqlTaskExecution.addSplitAssignments:256).
        Per-split string dictionaries are merged with codes remapped, so
        connectors may emit divergent dictionaries across splits (e.g.
        parquet row-group dictionaries).  Results are cached across queries
        when the connector is versioned-cacheable (DeviceScanCache)."""
        cache: Optional[DeviceScanCache] = self.config.get("scan_cache")
        # ALWAYS computed (even with caching off): the compiled-fragment
        # path keys on it, and streaming tiles must stay jitted — hive's
        # per-TABLE data_version walk is cheap (the table dir only)
        key = self._scan_cache_key(node, splits)
        if cache is not None and key is not None:
            hit = cache.get(key)
            if hit is not None:
                # re-bind cached arrays to this plan's symbols
                sym_of = {c: self._sym_for(node, c)
                          for _, c in node.assignments}
                merged = {}
                for col, lane in hit["merged"].items():
                    merged[sym_of[col]] = lane
                for col, d in hit["dicts"].items():
                    dicts[sym_of[col]] = d
                scans[id(node)] = merged
                counts[id(node)] = hit["total"]
                self._scan_keys[id(node)] = key
                self._scan_nodes[id(node)] = node
                self._scan_dictfp[id(node)] = hit.get("dictfp", 0)
                if hit.get("devgen") is not None:
                    # device-generated scan: keep the recipe so cleared
                    # dev arrays (graveyard retirement) can regenerate
                    self._devgen[id(node)] = hit["devgen"]
                return
        conn = self.catalogs.get(node.catalog)
        cols = [c for _, c in node.assignments]
        self._scan_nodes[id(node)] = node
        if self._try_device_generation(
            conn, node, cols, splits, key, cache, scans, dicts, counts
        ):
            return
        provider = conn.page_source_provider()
        tmap = dict(node.types)
        sym_of = {c: self._sym_for(node, c) for c in cols}
        pages: List[Page] = []
        for sp in splits:
            src = provider.create_page_source(sp, cols)
            for page in src.pages():
                src_dicts = src.dictionaries()
                new_cols = []
                for c, col in zip(page.names, page.columns):
                    d = (
                        col.dictionary
                        if col.dictionary is not None
                        else src_dicts.get(c)
                    )
                    new_cols.append(
                        Column(col.type, col.values, col.validity, d)
                    )
                pages.append(
                    Page(new_cols, page.count,
                         [sym_of[c] for c in page.names])
                )
        symbols = [sym_of[c] for c in cols]
        types = [(s, tmap[s]) for s in symbols]
        merged, total = merge_pages_to_arrays(pages, symbols, types, dicts)
        for s, t in types:
            # dict-typed symbols need a (possibly empty) dictionary even
            # when this task got zero splits/rows, for literal lowering
            if t.is_dictionary and s not in dicts:
                dicts[s] = np.array([], dtype=object)
        scans[id(node)] = merged
        counts[id(node)] = total
        self._scan_keys[id(node)] = key
        fp = dict_fingerprint(dicts, symbols)
        self._scan_dictfp[id(node)] = fp
        if cache is not None and key is not None:
            col_of = {s: c for s, c in node.assignments}
            host_merged = {col_of[s]: lane for s, lane in merged.items()}
            host_dicts = {
                col_of[s]: dicts[s] for s, _ in node.assignments
                if s in dicts
            }
            nbytes = sum(
                int(v.nbytes) + (int(ok.nbytes) if ok is not None else 0)
                for v, ok in merged.values()
            )
            cache.put(
                key,
                {"merged": host_merged, "dicts": host_dicts, "total": total,
                 "dev": {}, "dictfp": fp},
                nbytes,
            )

    def _jit_scan_component(self, nid):
        """Per-scan jit-key part: scan-cache key WITHOUT the split list,
        plus the dictionary-content fingerprint (dict codes are baked
        into traced programs as constants, so equal fingerprints are
        REQUIRED for a safe executable share — and sufficient, together
        with shapes, because the program reads nothing else from the
        split identity)."""
        key = self._scan_keys.get(nid)
        if key is None:
            # keyless sources (RemoteSource without a streaming cache)
            # still carry baked dictionaries: the fingerprint must stay
            # in the component or executables could outlive dict drift
            return (None, self._scan_dictfp.get(nid))
        no_splits = key[:4] + key[5:]
        return (no_splits, self._scan_dictfp.get(nid))

    def _try_device_generation(
        self, conn, node, cols, splits, key, cache, scans, dicts, counts
    ) -> bool:
        """On-device scan materialization: when the connector can produce
        every requested column as a pure function of the row index
        (counter-based generators — connectors/tpch_device.py), skip host
        arrays entirely; _device_lanes runs the generator program straight
        into HBM.  The reference's TPCH connector likewise generates rows
        in-process during the scan (TpchPageSourceProvider) — here the
        'process' is the chip."""
        devgen_fn = getattr(conn, "device_generation", None)
        if devgen_fn is None or not self.config.get(
            "device_generation", True
        ):
            return False
        try:
            spec = devgen_fn(node.table, cols, splits)
        except Exception:  # noqa: BLE001 — any trouble: host path
            spec = None
        if spec is None:
            return False
        sym_of = {c: self._sym_for(node, c) for c in cols}
        count = int(spec["count"])
        merged = {
            sym_of[c]: (
                _LazyDeviceLane(count * spec["widths"].get(c, 8)), None
            )
            for c in cols
        }
        tmap = dict(node.types)
        for c, d in spec["dicts"].items():
            dicts[sym_of[c]] = d
        for c in cols:
            s = sym_of[c]
            if tmap[s].is_dictionary and s not in dicts:
                dicts[s] = np.array([], dtype=object)
        scans[id(node)] = merged
        counts[id(node)] = count
        self._scan_keys[id(node)] = key
        symbols = [sym_of[c] for c in cols]
        fp = dict_fingerprint(dicts, symbols)
        self._scan_dictfp[id(node)] = fp
        self._devgen[id(node)] = spec
        if cache is not None and key is not None:
            col_of = {s: c for s, c in node.assignments}
            cache.put(
                key,
                {
                    "merged": {col_of[s]: merged[s] for s in merged},
                    "dicts": dict(spec["dicts"]),
                    "total": count, "dev": {}, "dictfp": fp,
                    "devgen": spec,
                },
                sum(lane[0].nbytes for lane in merged.values()),
            )
        return True

    def _generate_device_scan(self, spec: dict, syms, sym_to_col, cap):
        """Run the connector's on-device generator for one scan at padded
        capacity `cap`; returns {symbol: (values, ok)} resident in HBM.

        The generator is a first-dispatch kernel (fresh Mosaic compile per
        new (table, cols, cap) shape), so it runs under the supervisor like
        every other device program: the BENCH_r05 worker crash happened
        exactly here, outside any breadcrumb, which left the flight
        recorder blind to the culprit kernel.  The breadcrumb carries
        synthetic output-lane shapes (the generator has no host input
        arrays) so `scripts/flightrec.py replay` can reconstruct it."""
        from ..connectors import tpch_device

        cols = [sym_to_col.get(s, s) for s in syms]
        span = max(int(spec["hi"]) - int(spec["lo"]), 1)
        widths = spec.get("widths") or {}
        bc = self._dispatch_crumb(
            "devgen:%s" % spec["table"], "devgen"
        )
        bc.shapes = {
            c: "int%d(%d,)" % (8 * int(widths.get(c, 8)), cap)
            for c in cols
        }
        self.kernel_profile["last_breadcrumb"] = bc.to_dict()
        lanes = self._dispatch(
            lambda: tpch_device.device_lanes(
                spec["table"], cols, int(spec["lo"]), int(spec["hi"]), cap,
                float(spec["sf"]), int(spec["count"]),
                cap_orders=(
                    self.ladder.quantize(span)
                    if spec["table"] == "lineitem" else None
                ),
            ),
            bc,
        )
        return {s: lanes[c] for s, c in zip(syms, cols)}

    def _device_lanes(self, node: P.TableScan, arrays, count, nid=None):
        """Pad + upload one scan's host arrays to device lanes, reusing
        cached device arrays when the scan is version-cacheable (the
        host->HBM transfer dominates when the TPU is tunnel-attached).
        `nid` keys the scan-keys table for node-less sources (streaming
        RemoteSource inputs, cached per run)."""
        cap = self.ladder.quantize(count)
        override = int(self.config.get("scan_cap_override") or 0)
        if override and isinstance(node, P.TableScan):
            cap = max(cap, override)
        cache: Optional[DeviceScanCache] = self.config.get(
            "scan_cache"
        ) or getattr(self, "_streaming_cache", None)
        if nid is None and node is not None:
            nid = id(node)
        if nid is not None:
            # the rung actually dispatched — kernel profile and the
            # bandwidth ledger read padded bytes from here, so EXPLAIN
            # ANALYZE ratios match the observatory census
            self._scan_caps[nid] = cap
        # lanes staged ahead by FragmentExecutor.preupload (prefetch
        # thread): consume them instead of re-uploading.  Donatability
        # was recorded when they were staged.
        staged = getattr(self, "_preuploaded", None)
        if staged and nid in staged:
            return staged.pop(nid)
        key = self._scan_keys.get(nid) if nid is not None else None
        entry = (
            cache.get(key, record=False)
            if (cache is not None and key) else None
        )
        # RemoteSource (exchange input) reuses this load path but has no
        # column mapping and never caches (key is None for it)
        sym_to_col = {
            s: c for s, c in getattr(node, "assignments", None) or ()
        }
        # lanes with no cache entry are per-dispatch uploads nothing else
        # references: the fused jit may donate their buffers back to XLA
        # (cache-resident lanes are reused across tiles/queries and must
        # survive the dispatch)
        donatable = getattr(self, "_lane_donatable", None)
        if donatable is None:
            donatable = self._lane_donatable = {}
        if nid is not None:
            donatable[nid] = entry is None
        lanes = {}
        gen_out = None
        for sym, (arr, valid) in arrays.items():
            col = sym_to_col.get(sym, sym)
            if entry is not None and col in entry["dev"]:
                lanes[sym] = entry["dev"][col]
                continue
            if isinstance(arr, _LazyDeviceLane):
                if gen_out is None:
                    spec = self._devgen.get(nid)
                    lazy_syms = [
                        s for s, (a, _v) in arrays.items()
                        if isinstance(a, _LazyDeviceLane)
                        and not (entry is not None
                                 and sym_to_col.get(s, s) in entry["dev"])
                    ]
                    gen_out = self._generate_device_scan(
                        spec, lazy_syms, sym_to_col, cap
                    )
                lanes[sym] = gen_out[sym]
                if entry is not None:
                    entry["dev"][col] = gen_out[sym]
                continue
            if arr.shape[0] < cap:
                pad = np.zeros(
                    (cap - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype
                )
                arr = np.concatenate([arr, pad])
            v = jnp.asarray(arr)
            if valid is None:
                ok = jnp.ones(cap, dtype=bool)
            else:
                vv = np.zeros(cap, dtype=bool)
                vv[: valid.shape[0]] = valid
                ok = jnp.asarray(vv)
            lanes[sym] = (v, ok)
            if entry is not None:
                entry["dev"][col] = (v, ok)
        return lanes

    @staticmethod
    def _sym_for(scan: P.TableScan, col: str) -> str:
        for s, c in scan.assignments:
            if c == col:
                return s
        raise KeyError(col)

    # ------------------------------------------------------------------
    def _estimate_group_capacity(self, plan: P.PlanNode, counts) -> Optional[int]:
        """Initial sort-group-by capacity from connector NDV statistics
        (the CBO's AggregationStatsRule role): every overflow rung re-runs
        and re-compiles the fragment, so landing near the real group count
        on the first try matters.  Bounded by the scan row count (a group
        per input row at worst)."""
        ndv: Dict[str, float] = {}
        max_rows = max(counts.values(), default=0)

        def walk(n: P.PlanNode):
            if isinstance(n, P.TableScan):
                try:
                    stats = self.metadata.table_statistics(n.catalog, n.table)
                except Exception:
                    return
                for sym, col in n.assignments:
                    cs = stats.columns.get(col)
                    if cs is not None and cs.distinct_count:
                        ndv[sym] = cs.distinct_count
                    else:
                        ndv.setdefault(sym, stats.row_count)
            for s in n.sources:
                walk(s)

        walk(plan)
        best = None

        def walk2(n: P.PlanNode):
            nonlocal best
            if isinstance(n, P.Aggregate) and n.keys:
                est = 1.0
                for k in n.keys:
                    est *= ndv.get(k, float(DEFAULT_GROUP_CAPACITY))
                    if est > 1e12:
                        break
                est = min(est, float(max_rows) or est)
                best = max(best or 0, int(est))
            for s in n.sources:
                walk2(s)

        walk2(plan)
        if best is None or best <= DEFAULT_GROUP_CAPACITY:
            return None
        # NDV products wildly overestimate for correlated keys (brand_id
        # determines brand; orderkey determines orderdate), and every
        # segment op pays O(capacity).  Cap the first try; the overflow
        # ladder (x8 per rung) covers genuinely huge group counts with one
        # recompile instead of every query paying worst-case capacity.
        return self.ladder.quantize(min(best * 2, max_rows, 1 << 18))

    # ------------------------------------------------------------------
    def _compile_family(self, plan) -> str:
        """Shape- and capacity-invariant kernel-family digest: the
        observatory's unit of 'same program modulo padding bucket'."""
        from ..cache.compile_cache import stable_key_digest
        from ..cache.signature import fragment_fingerprint

        try:
            fp = fragment_fingerprint(plan)
        except Exception:  # unknown node kinds: per-object identity
            fp = id(plan)
        return stable_key_digest(("family", fp))[:12]

    def _compile_shape_sig(self, counts) -> str:
        """Ladder-rung signature of one execution's scan shapes (the
        eager/mesh analog of the jit key's per-scan bucket component)."""
        from ..cache.compile_cache import stable_key_digest

        return stable_key_digest(tuple(sorted(
            self.ladder.quantize(int(c)) for c in counts.values()
        )))[:12]

    def _dispatched_cap(self, nid, count: int) -> int:
        """The padded capacity actually dispatched for one scan: the
        recorded rung when `_device_lanes` ran (includes any
        scan_cap_override), the ladder's rung otherwise."""
        cap = self._scan_caps.get(nid)
        return int(cap) if cap else self.ladder.quantize(int(count))

    def _padded_rows(self, counts) -> int:
        """Total dispatched padded rows across the fragment's scans —
        what the observatory census and EXPLAIN ANALYZE both report."""
        return sum(
            self._dispatched_cap(nid, int(c)) for nid, c in counts.items()
        )

    def _record_kernel(
        self, digest: str, compile_s: float, cached: bool, mode: str = "jit",
        cause: Optional[str] = None,
    ) -> dict:
        """Accumulate one fragment-program execution into kernel_profile."""
        kernels: List[dict] = self.kernel_profile["kernels"]  # type: ignore[assignment]
        rec = None
        for k in kernels:
            if k["digest"] == digest:
                rec = k
                break
        if rec is None:
            rec = {
                "digest": digest,
                "mode": mode,
                "compiles": 0,
                "compileWallS": 0.0,
                "executions": 0,
                "cacheHits": 0,
                "causes": {},
            }
            kernels.append(rec)
        rec["executions"] += 1
        if cached:
            rec["cacheHits"] += 1
        else:
            rec["compiles"] += 1
            rec["compileWallS"] += compile_s
            cause = cause or _compile_obs.FIRST_COMPILE
            causes = rec.setdefault("causes", {})
            causes[cause] = causes.get(cause, 0) + 1
            REGISTRY.histogram(
                "trino_tpu_kernel_compile_seconds",
                "XLA fragment compile (or eager trace) wall time",
            ).observe(compile_s)
            if cause != _compile_obs.FIRST_COMPILE:
                # recompiles split by the observatory's cause taxonomy:
                # ladder rungs, shape misses, poison recovery,
                # persistent-tier loads — no longer conflated
                REGISTRY.counter(
                    "trino_tpu_kernel_recompile_total",
                    "Fragment programs compiled beyond a family's first,"
                    " by cause",
                ).inc(cause=cause)
        return rec

    def _finalize_kernel_profile(self, scans, counts, host_lanes, sel_np):
        """Fill the profile summary once the fragment settles: padding
        waste and estimated host<->device transfer volume."""
        actual = sum(int(c) for c in counts.values())
        padded = self._padded_rows(counts)
        h2d = 0
        for nid, arrays in scans.items():
            count = max(int(counts.get(nid, 1)), 1)
            scale = self._dispatched_cap(nid, count) / count
            for v, ok in arrays.values():
                nb = int(v.nbytes) + (int(ok.nbytes) if ok is not None else 0)
                h2d += int(nb * scale)
        d2h = int(getattr(sel_np, "nbytes", 0))
        for v, ok in host_lanes.values():
            d2h += int(getattr(v, "nbytes", 0))
            d2h += int(getattr(ok, "nbytes", 0)) if ok is not None else 0
        kernels: List[dict] = self.kernel_profile["kernels"]  # type: ignore[assignment]
        compiles = sum(k["compiles"] for k in kernels)
        by_cause: Dict[str, int] = {}
        for k in kernels:
            for c, n in (k.get("causes") or {}).items():
                by_cause[c] = by_cause.get(c, 0) + n
        self.kernel_profile["summary"] = {
            "kernels": len(kernels),
            "compiles": compiles,
            # a recompile is any compile whose cause is NOT a family's
            # first — the old max(0, compiles - 1) conflated ladder
            # rungs, poison recovery, and genuine shape misses
            "recompiles": max(
                0,
                compiles - by_cause.get(_compile_obs.FIRST_COMPILE, 0),
            ),
            "compilesByCause": by_cause,
            "cacheHits": sum(k["cacheHits"] for k in kernels),
            "compileWallS": sum(k["compileWallS"] for k in kernels),
            "actualRows": actual,
            "paddedRows": padded,
            "paddingRatio": (padded / actual) if actual else 1.0,
            "h2dBytes": h2d,
            "d2hBytes": d2h,
        }
        REGISTRY.counter(
            "trino_tpu_kernel_h2d_bytes", "Estimated host-to-device scan upload bytes"
        ).inc(h2d)
        REGISTRY.counter(
            "trino_tpu_kernel_d2h_bytes", "Estimated device-to-host result bytes"
        ).inc(d2h)
        led = self.bandwidth_ledger
        if led is not None:
            s = led.summary()
            self.kernel_profile["bandwidth"] = led.entries()
            self.kernel_profile["summary"].update(
                effectiveGbps=s["effectiveGbps"],
                rooflinePct=s["rooflinePct"],
                ledgerBytes=s["totalBytes"],
                deviceWallS=s["deviceWallS"],
            )

    # ------------------------------------------------------------------
    def _run_jitted(self, plan: P.Output, scans, counts):
        """One jitted XLA program per fragment (the architecture's codegen
        slot: LocalExecutionPlanner -> generated bytecode in the reference,
        -> one traced+compiled jax function here).  The compiled callable is
        cached per (plan, shapes, capacities) in the session-owned jit
        cache; eager mode remains for EXPLAIN ANALYZE and host-staged
        operators (UNNEST)."""
        cache = self.config.get("jit_cache")
        if cache is None:
            cache = {}
        # the key is built by the cache subsystem: (fragment fingerprint,
        # capacity ladder state, per-scan shape bucket + versioned scan
        # identity + dict fingerprint), with plan-local ids translated to
        # traversal ordinals — a compiled program is a pure function of
        # (plan, capacities, padded lane shapes, BAKED dictionary
        # contents), NOT of which splits produced the rows or which
        # session traced it, so structurally identical fragments from
        # other sessions (or, via the persistent tier, other processes)
        # share one executable.
        from ..cache.compile_cache import fragment_key, stable_key_digest

        key, order, by_ord = fragment_key(
            self, plan, scans, counts, self.ladder.quantize
        )
        # prep is keyed by plan ordinal, NOT id(node): dict keys are part
        # of the jit pytree structure, so id-based keys would force a
        # retrace (into the WRONG captured plan) for every session sharing
        # an entry; ordinals make the structure session-invariant
        prep = {}
        donatable_ords = set()
        for nid, arrays in scans.items():
            lanes = dict(self._device_lanes(
                self._scan_nodes.get(nid), arrays, counts[nid], nid
            ))
            # the true row count rides as a TRACED scalar: baking it as
            # a constant would specialize the executable per exact count
            # (streaming tiles differ by a few rows while sharing the
            # padded shape — they must share one program)
            lanes["__count__"] = jnp.asarray(counts[nid], dtype=jnp.int64)
            o = order.get(nid, nid)
            prep[o] = lanes
            if getattr(self, "_lane_donatable", {}).get(nid):
                donatable_ords.add(o)
        # donation split: per-dispatch scan uploads ride in a separate
        # pytree arg the compiled program may consume in place
        # (donate_argnums, per the pjit residency protocol) — the
        # copy-on-write round trip for every tile page disappears.
        # Cache-resident lanes (scan cache hits, streaming build tables)
        # stay in the non-donated arg.  CPU donation is a no-op warning,
        # so only a real accelerator backend donates.
        donate = (
            bool(self.config.get("donate_pages", True))
            and not self._device_fallback
            and jax.default_backend() != "cpu"
        )
        if not donate:
            donatable_ords = set()
        # the split is part of the traced structure AND of the executable
        # contract, so it keys the cache alongside the fused-agg mode
        key = key + (
            ("donate", donate, tuple(sorted(donatable_ords))),
            ("megakernels", self._megakernel_mode()),
        )
        digest = stable_key_digest(key)[:12]
        self._last_jit_key = key
        resident_prep = {
            o: v for o, v in prep.items() if o not in donatable_ords
        }
        tile_prep = {
            o: v for o, v in prep.items() if o in donatable_ords
        }
        if donate and tile_prep:
            self.kernel_profile["donated_dispatches"] = (
                self.kernel_profile.get("donated_dispatches", 0) + 1
            )
            self.kernel_profile["donated_bytes"] = (
                self.kernel_profile.get("donated_bytes", 0)
                + sum(
                    int(getattr(x, "nbytes", 0) or 0)
                    for lanes in tile_prep.values()
                    for lane in lanes.values()
                    for x in (lane if isinstance(lane, tuple) else (lane,))
                )
            )
        entry = cache.get(key)
        if entry is None:
            cell: Dict[str, object] = {}
            # ordinal -> id(node) of the TRACING plan, for the closure
            ids = {o: i for i, o in order.items()}

            def raw(resident_arg, tile_arg):
                prep_arg = dict(resident_arg)
                prep_arg.update(tile_arg)
                ctx = self.trace_ctx_cls(
                    self,
                    {ids.get(o, o): v for o, v in prep_arg.items()},
                    counts,
                )
                ctx.prepared = True
                out_lanes, sel, ordered, checks = self._run(plan, ctx)
                cell["ordered"] = ordered
                cell["caps"] = [(c, k) for _, c, k in checks]
                # dup-check join nodes are recorded as plan ordinals so a
                # different session hitting this entry resolves them to
                # ITS OWN plan's node objects (force sets are id-based)
                cell["dup_ords"] = [
                    order.get(id(n), -1) for n, _ in ctx.dup_checks
                ]
                return (
                    out_lanes,
                    sel,
                    tuple(ng for ng, _, _ in checks),
                    tuple(d for _, d in ctx.dup_checks),
                    tuple(ctx.collision_checks),
                    tuple(ctx.lowering.overflow_flags),
                    tuple(ctx.sum_overflow),
                )

            compile_start = time.time()
            bc = self._dispatch_crumb(digest, "jit", prep)
            self._last_crumb = bc
            # observatory cause, classified BEFORE the compile so the
            # tracer span carries it: poisoned recovery > ladder rung >
            # persistent-tier load > shape miss vs first compile
            family = self._compile_family(plan)
            poisoned = key in getattr(self, "_poisoned_jit_keys", ())
            persistent = bool(
                getattr(cache, "persistent_known", None) is not None
                and cache.persistent_known(key)
            )
            ladder_attempt = int(getattr(self, "_ladder_attempt", 0))
            cause = _compile_obs.get_observatory().classify(
                family, digest, ladder_attempt=ladder_attempt,
                poisoned=poisoned, persistent=persistent,
                query_id=self.query_id,
            )
            shapes = _shape_summary(prep)
            actual_rows = sum(int(c) for c in counts.values())
            padded_rows = self._padded_rows(counts)
            with TRACER.span(
                "xla_compile", fragment=digest, cause=cause,
                shapeSig=";".join(
                    "%s=%s" % kv for kv in sorted(shapes.items())
                ),
                actualRows=actual_rows, paddedRows=padded_rows,
                paddedRatio=round(
                    padded_rows / actual_rows, 3
                ) if actual_rows else 1.0,
            ):
                if donate and donatable_ords:
                    fn = jax.jit(  # dispatch-guard: ok (lazy wrapper)
                        raw, donate_argnums=(1,)
                    )
                else:
                    # no-donate: cpu backend / every lane cache-resident
                    fn = jax.jit(raw)  # dispatch-guard: ok (lazy wrapper)
                led_t0 = time.perf_counter()
                out = self._dispatch(
                    lambda: fn(resident_prep, tile_prep), bc
                )
                # cold entry: the bracketing wall includes trace+compile
                # (inseparable under jax.jit); warm executions dominate
                # the accumulated GB/s
                self._ledger_bracket(out, digest, "jit", plan, scans, led_t0)
            compile_s = time.time() - compile_start
            _compile_obs.record_compile(
                kernel=digest, family=family, cause=cause,
                mode="jit", shapes=shapes,
                actual_rows=actual_rows, padded_rows=padded_rows,
                compile_wall_s=compile_s,
                query_id=self.query_id,
                task_id=str(self.config.get("task_id") or ""),
                node_id=str(self.config.get("node_id") or ""),
                scan_rows=[int(c) for c in counts.values()],
            )
            self._record_kernel(
                digest, compile_s=compile_s, cached=False, cause=cause
            )
            cell["dicts"] = dict(self.dicts)
            # the plan reference pins id(plan) (fingerprint memo validity)
            entry = {"fn": fn, "cell": cell, "plan": plan}
            cache[key] = entry
        else:
            cell = entry["cell"]
            self.dicts.update(cell["dicts"])
            # dispatch is async: a tunnel re-dispatch fault surfaces at the
            # execute() loop's device_get, whose handler evicts the
            # poisoned entry and recompiles exactly once (INVALID_ARGUMENT
            # only, never OOM)
            bc = self._dispatch_crumb(digest, "jit", prep)
            self._last_crumb = bc
            led_t0 = time.perf_counter()
            out = self._dispatch(
                lambda: entry["fn"](resident_prep, tile_prep), bc
            )
            self._ledger_bracket(out, digest, "jit", plan, scans, led_t0)
            self._record_kernel(digest, compile_s=0.0, cached=True)
        out_lanes, sel, ngroups, dup_vals, colls, wides, sflags = out
        checks = [
            (ng, cap, kind)
            for ng, (cap, kind) in zip(ngroups, cell["caps"])
        ]
        dups = [
            (by_ord.get(o), d) for o, d in zip(cell["dup_ords"], dup_vals)
        ]
        return (out_lanes, sel, cell["ordered"], checks, dups, colls,
                wides, sflags)

    # ------------------------------------------------------------------
    def _run(self, plan: P.Output, ctx: "_TraceCtx"):
        batch = ctx.visit(plan.source)
        out = {s: batch.lanes[s] for s in plan.symbols}
        return out, batch.sel, batch.ordered, ctx.capacity_checks

    # ------------------------------------------------------------------
    def _materialize(self, plan: P.Output, lanes, sel, ordered) -> Page:
        # single device->host transfer for the selection mask and every
        # output lane (per-array np.asarray would pay one tunnel RTT each)
        last = getattr(self, "_last_crumb", None)
        host_lanes, sel_np = self._device_get(
            ({s: lanes[s] for s in plan.symbols}, sel),
            self._dispatch_crumb(
                last.kernel if last else "materialize", "device_get"
            ),
        )
        return self._materialize_host(plan, host_lanes, sel_np)

    def _materialize_host(self, plan: P.Output, host_lanes, sel_np) -> Page:
        types = plan.source.output_types()
        cols = []
        idx = np.nonzero(sel_np)[0]
        n = len(idx)
        for name, sym in zip(plan.names, plan.symbols):
            v, ok = host_lanes[sym]
            vals = v[idx]
            valid = ok[idx]
            t = types[sym]
            if getattr(t, "wide", False) and vals.ndim == 1:
                # lane-narrow/type-wide (fast-path arithmetic kept one
                # limb): widen host-side so clients decode two limbs
                vals = np.stack([vals, vals >> np.int64(63)], axis=-1)
            validity = None if valid.all() else valid
            cols.append(Column(t, vals, validity, self.dicts.get(sym)))
        return Page(cols, n, list(plan.names))


class _TraceCtx:
    """One trace of the plan (shapes fixed by the loaded scan sizes)."""

    def __init__(self, ex: LocalExecutor, scans, counts):
        self.ex = ex
        self.scans = scans
        self.counts = counts
        self.capacity_checks: List[Tuple[jnp.ndarray, int]] = []
        self.dup_checks: List[Tuple[P.PlanNode, jnp.ndarray]] = []
        self.collision_checks: List[jnp.ndarray] = []
        # BIGINT sum-accumulator overflow flags (decimal sums are exact
        # via wide chunk accumulators; bigint wrap raises loudly per SQL
        # semantics, never silently)
        self.sum_overflow: List[jnp.ndarray] = []
        self.lowering = LoweringContext(ex.dicts)
        self.lowering.force_wide_mul = getattr(ex, 'force_wide_mul', False)

    # -- dispatch -------------------------------------------------------
    def visit(self, node: P.PlanNode) -> Batch:
        m = getattr(self, f"_visit_{type(node).__name__.lower()}", None)
        if m is None:
            raise ExecutionError(f"no executor for {type(node).__name__}")
        if not self.ex.config.get("collect_node_stats"):
            return m(node)
        # EXPLAIN ANALYZE instrumentation (OperatorContext timing analog);
        # wall time is inclusive of children — the printer (and
        # obs/opstats.frames_from_plan) subtracts.  The dispatch-to-sync
        # split approximates host (trace + dispatch) vs device (waiting
        # on the computation) wall in eager mode.
        import time as _time

        t0 = _time.perf_counter()
        b = m(node)
        t1 = _time.perf_counter()
        # EXPLAIN ANALYZE timing sync; runs inside the supervised eager
        # dispatch, so it is already covered by the boundary
        jax.block_until_ready((b.sel,))  # dispatch-guard: ok
        t2 = _time.perf_counter()
        st = self.ex.node_stats.setdefault(
            id(node),
            {"rows": 0, "bytes": 0, "wall_s": 0.0,
             "device_wall_s": 0.0, "calls": 0},
        )
        rows = int(jnp.sum(b.sel))
        cap = int(b.sel.shape[0]) if getattr(b.sel, "shape", None) else 0
        lane_bytes = 0
        for v in b.lanes.values():
            parts = v if isinstance(v, tuple) else (v,)
            lane_bytes += sum(
                int(getattr(p, "nbytes", 0))
                for p in parts if p is not None
            )
        st["rows"] = rows
        # logical (unpadded) bytes: padded lane footprint scaled by the
        # live-row fraction, matching rows x width hand-computation
        st["bytes"] = (
            int(lane_bytes * rows / cap) if cap else lane_bytes
        )
        st["wall_s"] += t2 - t0
        st["device_wall_s"] = st.get("device_wall_s", 0.0) + (t2 - t1)
        st["calls"] += 1
        return b

    # -- leaves ---------------------------------------------------------
    def _visit_tablescan(self, node: P.TableScan) -> Batch:
        count = self.counts[id(node)]
        cap = self.ex.ladder.quantize(count)
        override = int(self.ex.config.get("scan_cap_override") or 0)
        if override and isinstance(node, P.TableScan):
            # streaming tiles share one padded shape (and therefore one
            # compiled program) even when their exact row counts differ
            cap = max(cap, override)
        if getattr(self, "prepared", False):
            # jitted-fragment mode: lanes are traced jit arguments and
            # the true row count is the traced "__count__" scalar
            lanes = dict(self.scans[id(node)])
            cnt = lanes.pop("__count__", count)
        else:
            lanes = self.ex._device_lanes(node, self.scans[id(node)], count)
            cnt = count
        sel = jnp.arange(cap) < cnt
        return Batch(lanes, sel)

    def _visit_values(self, node: P.Values) -> Batch:
        n = len(node.rows)
        cap = self.ex.ladder.quantize(max(n, 1))
        lanes = {}
        tmap = dict(node.types_)
        for sym, d in getattr(node, "dicts", ()):
            self.ex.dicts[sym] = np.array(list(d), dtype=object)
        for i, sym in enumerate(node.symbols):
            colvals = [r[i] for r in node.rows]
            t = tmap[sym]
            ok = np.zeros(cap, dtype=bool)
            if getattr(t, "wide", False):
                from ..ops.wide_decimal import from_python_int

                arr = np.zeros((cap, 2), dtype=np.int64)
                for j, v in enumerate(colvals):
                    if v is not None:
                        arr[j, 0], arr[j, 1] = from_python_int(int(v))
                        ok[j] = True
            else:
                arr = np.zeros(cap, dtype=t.np_dtype)
                for j, v in enumerate(colvals):
                    if v is not None:
                        arr[j] = v
                        ok[j] = True
            lanes[sym] = (jnp.asarray(arr), jnp.asarray(ok))
        sel = jnp.arange(cap) < n
        return Batch(lanes, sel)

    # -- unary ----------------------------------------------------------
    def _visit_sample(self, node: P.Sample) -> Batch:
        b = self.visit(node.source)
        n = b.sel.shape[0]
        # deterministic splitmix64 of the row index -> uniform [0, 1)
        z = jnp.arange(n, dtype=jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> 27)) * jnp.uint64(0x94D049BB133111EB)
        z = z ^ (z >> 31)
        u = (z >> 11).astype(jnp.float64) / float(1 << 53)
        keep = u < node.fraction
        return Batch(b.lanes, b.sel & keep, b.ordered, b.replicated)

    # single-device trace: compaction capacities are global row counts;
    # mesh shards see 1/ndev of the rows, so _MeshTraceCtx disables this
    allow_compaction = True

    def _maybe_compact(self, b: Batch, node) -> Batch:
        """Tighten survivors into a smaller static capacity (the
        optimizer's compact_rows estimate, grown by the ladder's
        compact_factor).  One jnp.nonzero + one stacked row-gather;
        every downstream sort/gather then runs at the tightened width
        and the fragment's HBM peak shrinks with it.  Exactness: the
        true survivor count rides the capacity checks — overflow re-runs
        with a wider (eventually input-width, i.e. no-op) capacity."""
        est = getattr(node, "compact_rows", None)
        if (
            est is None
            or not self.allow_compaction
            or b.ordered
            or b.replicated
        ):
            return b
        factor = getattr(self.ex, "compact_factor", 1)
        cap = self.ex.ladder.quantize(int(est * 1.3) * factor)
        n = b.sel.shape[0]
        if cap >= n:
            return b
        from ..ops.filter_project import permute_lanes

        total = b.sel.sum()
        idx = jnp.nonzero(b.sel, size=cap, fill_value=0)[0]
        self._note_capacity(total, cap, "compact")
        lanes = permute_lanes(b.lanes, idx)
        sel = jnp.arange(cap) < total
        return Batch(lanes, sel, b.ordered, b.replicated)

    def _visit_filter(self, node: P.Filter) -> Batch:
        b = self.visit(node.source)
        f = compile_expr(node.predicate, self.lowering)
        v, ok = f(b.lanes)
        out = Batch(b.lanes, b.sel & v & ok, b.ordered, b.replicated)
        return self._maybe_compact(out, node)

    def _visit_project(self, node: P.Project) -> Batch:
        b = self.visit(node.source)
        out = {}
        for sym, e in node.assignments:
            out[sym] = compile_expr(e, self.lowering)(b.lanes)
            # propagate dictionaries: pass-through refs and derived strings
            if isinstance(e, ir.ColumnRef) and e.name in self.ex.dicts:
                self.ex.dicts[sym] = self.ex.dicts[e.name]
            else:
                d = self.lowering.dict_for_expr(e)
                if d is not None:
                    self.ex.dicts[sym] = d
                elif e.type.is_dictionary and _is_null_expr(e):
                    # NULL literal projected as varchar (e.g. unmentioned
                    # MERGE insert columns): every row invalid, empty dict
                    self.ex.dicts[sym] = np.array([], dtype=object)
        return Batch(out, b.sel, b.ordered, b.replicated)

    def _visit_limit(self, node: P.Limit) -> Batch:
        b = self.visit(node.source)
        lanes, sel = sort_ops.limit(
            b.lanes, b.sel, node.count, node.offset
        )
        return Batch(lanes, sel, b.ordered, b.replicated)

    def _visit_distinct(self, node: P.Distinct) -> Batch:
        b = self.visit(node.source)
        syms = node.output_symbols()
        key_lanes = [b.lanes[s] for s in syms]
        cap = b.sel.shape[0]
        perm, gid, ngroups = self._group_sort(key_lanes, b.sel, cap)
        sel_sorted = b.sel[perm]
        boundary = jnp.concatenate(
            [jnp.ones(1, dtype=bool), gid[1:] != gid[:-1]]
        )
        lanes = {
            s: (v[perm], ok[perm]) for s, (v, ok) in b.lanes.items()
        }
        return Batch(lanes, sel_sorted & boundary)

    def _visit_unnest(self, node: P.Unnest) -> Batch:
        """UNNEST via host-side expansion: lengths come from the array
        dictionary, rows replicate with np.repeat, elements flatten into a
        fresh lane (UnnestOperator's row-replication, staged on host since
        output size is data-dependent — the same reason the reference
        streams it row-by-row)."""
        b = self.visit(node.source)
        sel = np.asarray(b.sel)
        rows = np.nonzero(sel)[0]
        av, aok = b.lanes[node.array_symbol]
        codes = np.asarray(av)[rows]
        avalid = np.asarray(aok)[rows]
        entries = self.ex.dicts.get(node.array_symbol)
        if entries is None:
            raise ExecutionError(
                f"no dictionary for array column {node.array_symbol}"
            )
        lengths = np.array(
            [
                len(entries[c]) if (ok and c >= 0) else 0
                for c, ok in zip(codes, avalid)
            ],
            dtype=np.int64,
        )
        eff = np.maximum(lengths, 1) if node.outer else lengths
        total = int(eff.sum())
        cap = self.ex.ladder.quantize(max(total, 1))
        rep = np.repeat(rows, eff)  # source row per output row
        elems: list = []
        for c, ok, ln in zip(codes, avalid, lengths):
            if ln:
                elems.extend(entries[c])
            elif node.outer:
                elems.append(None)  # LEFT JOIN UNNEST: NULL element row
        lanes = {}
        for sym, (v, ok) in b.lanes.items():
            if sym == node.array_symbol:
                continue
            vv = np.asarray(v)[rep]
            vo = np.asarray(ok)[rep]
            lanes[sym] = (
                jnp.asarray(pad_to(vv, cap)),
                jnp.asarray(pad_to(vo, cap, False)),
            )
        et = node.element_type
        from ..page import column_from_pylist

        if et.is_dictionary and not getattr(et, "is_array", False):
            col = column_from_pylist(et, elems)
            self.ex.dicts[node.element_symbol] = col.dictionary
            ev = col.values
            eo = (
                np.ones(total, dtype=bool)
                if col.validity is None
                else col.validity
            )
        elif getattr(et, "is_array", False):
            raise ExecutionError("UNNEST of nested arrays is not supported")
        else:
            ev = np.array(
                [0 if x is None else x for x in elems], dtype=et.np_dtype
            )
            eo = np.array([x is not None for x in elems], dtype=bool)
        lanes[node.element_symbol] = (
            jnp.asarray(pad_to(ev, cap)),
            jnp.asarray(pad_to(eo, cap, False)),
        )
        if node.ordinality_symbol:
            ovals: list = []
            ovalid: list = []
            for ln in lengths:
                if ln:
                    ovals.extend(range(1, int(ln) + 1))
                    ovalid.extend([True] * int(ln))
                elif node.outer:  # null-extended row: ordinality is NULL
                    ovals.append(0)
                    ovalid.append(False)
            lanes[node.ordinality_symbol] = (
                jnp.asarray(pad_to(np.array(ovals, dtype=np.int64), cap)),
                jnp.asarray(
                    pad_to(np.array(ovalid, dtype=bool), cap, False)
                ),
            )
        return Batch(lanes, jnp.arange(cap) < total)

    def _visit_matchrecognize(self, node: P.MatchRecognize) -> Batch:
        """MATCH_RECOGNIZE, host-staged (output size is data-dependent and
        the automaton is inherently sequential per partition — the
        reference's window/matcher is also a row-at-a-time NFA)."""
        import functools

        from ..ops.matcher import find_matches

        b = self.visit(node.source)
        sel = np.asarray(b.sel)
        rows = np.nonzero(sel)[0]
        n = len(rows)
        src_types = node.source.output_types()
        cols: Dict[str, list] = {}
        for sym in node.source.output_symbols():
            if sym not in b.lanes:
                continue
            v, ok = b.lanes[sym]
            vv = np.asarray(v)[rows]
            oo = np.asarray(ok)[rows]
            t = src_types[sym]
            if t.is_dictionary and not getattr(t, "is_array", False):
                d = self.ex.dicts.get(sym)
                cols[sym] = [
                    (str(d[int(c)]) if (okk and int(c) >= 0) else None)
                    for c, okk in zip(vv, oo)
                ]
            else:
                cols[sym] = [
                    (v_.item() if okk else None)
                    for v_, okk in zip(vv, oo)
                ]
        # order rows: partition keys first, then ORDER BY keys
        keys = [(s, True, False) for s in node.partition_by] + [
            (k.column, k.ascending, k.nulls_first)
            for k in node.order_by
        ]

        def cmp(a, bidx):
            for col, asc, nulls_first in keys:
                va, vb = cols[col][a], cols[col][bidx]
                if va is None and vb is None:
                    continue
                if va is None:
                    return -1 if nulls_first else 1
                if vb is None:
                    return 1 if nulls_first else -1
                if va == vb:
                    continue
                lt = va < vb
                return (-1 if lt else 1) if asc else (1 if lt else -1)
            return 0

        order = sorted(range(n), key=functools.cmp_to_key(cmp))
        defines = dict(node.defines)
        measures = [(s, e) for s, e, _ in node.measures]
        out_rows: List[dict] = []
        i = 0
        while i < n:
            j = i
            pkey = tuple(cols[s][order[i]] for s in node.partition_by)
            while j < n and tuple(
                cols[s][order[j]] for s in node.partition_by
            ) == pkey:
                j += 1
            part_idx = order[i:j]
            pcols = {c: [vals[k] for k in part_idx] for c, vals in cols.items()}
            all_rows = node.rows_per_match == "all"
            for m in find_matches(
                pcols, len(part_idx), node.pattern, defines, measures,
                node.after_match, all_rows,
            ):
                if all_rows:
                    r = m.pop("__row__")
                    for c in pcols:
                        m[c] = pcols[c][r]
                else:
                    for s, v in zip(node.partition_by, pkey):
                        m[s] = v
                out_rows.append(m)
            i = j
        total = len(out_rows)
        cap = self.ex.ladder.quantize(max(total, 1))
        out_types = node.output_types()
        lanes = {}
        from ..page import column_from_pylist

        for sym in node.output_symbols():
            t = out_types[sym]
            vals = [m.get(sym) for m in out_rows]
            if t.is_dictionary and not getattr(t, "is_array", False):
                col = column_from_pylist(t, vals)
                self.ex.dicts[sym] = col.dictionary
                arr = np.asarray(col.values)
                okv = (
                    np.ones(total, bool) if col.validity is None
                    else np.asarray(col.validity)
                )
            else:
                arr = np.array(
                    [0 if x is None else x for x in vals], dtype=t.np_dtype
                )
                okv = np.array([x is not None for x in vals], dtype=bool)
            lanes[sym] = (
                jnp.asarray(pad_to(arr, cap)),
                jnp.asarray(pad_to(okv, cap, False)),
            )
        return Batch(lanes, jnp.arange(cap) < total)

    def _visit_groupid(self, node: P.GroupId) -> Batch:
        """GROUPING SETS row expansion: tile every lane once per grouping
        set and mask grouping keys absent from each set to NULL; a
        replicated [0..G) group-id lane distinguishes the copies."""
        b = self.visit(node.source)
        G = len(node.sets)
        n = b.sel.shape[0]
        key_union = {s for st in node.sets for s in st}
        lanes = {}
        for sym, (v, ok) in b.lanes.items():
            v2 = jnp.tile(v, G)
            ok2 = jnp.tile(ok, G)
            if sym in key_union and any(sym not in st for st in node.sets):
                keep = np.array([sym in st for st in node.sets], dtype=bool)
                ok2 = ok2 & jnp.repeat(jnp.asarray(keep), n)
            lanes[sym] = (v2, ok2)
        gid = jnp.repeat(jnp.arange(G, dtype=jnp.int64), n)
        lanes[node.gid_symbol] = (gid, jnp.ones(G * n, dtype=bool))
        return Batch(lanes, jnp.tile(b.sel, G), replicated=b.replicated)

    # -- aggregation -----------------------------------------------------
    def _visit_aggregate(self, node: P.Aggregate, b: Optional[Batch] = None) -> Batch:
        """Handles all three steps (AggregationNode.java:346): SINGLE and
        PARTIAL accumulate raw rows; FINAL merges shipped accumulator
        columns (the distributed merge path)."""
        if b is None:
            if node.step in ("single", "partial"):
                from ..ops import megakernel

                fused = megakernel.try_fused(self, node)
                if fused is not None:
                    return fused
            b = self.visit(node.source)
        types = node.source.output_types()
        b, aggs = self._agg_dict_setup(node, b)
        all_specs = [a.to_spec() for a in aggs]
        host_specs = [
            s for s in all_specs if s.kind in agg_ops.HOST_STAGED_KINDS
        ]
        specs = [
            s for s in all_specs if s.kind not in agg_ops.HOST_STAGED_KINDS
        ]
        final = node.step in ("final", "intermediate")  # merges accumulators
        partial = node.step in ("partial", "intermediate")  # emits them
        if host_specs and (final or partial):
            raise ExecutionError(
                "host-staged aggregates cannot split PARTIAL/FINAL"
            )

        def reduce_rows(lanes, gid, sel, cap, seg=None):
            if final:
                acc_in = {
                    n: lanes[n] for s in specs for n in s.accumulator_names
                }
                return agg_ops.merge_accumulators(
                    specs, acc_in, gid, sel, cap,
                    overflow_flags=self.sum_overflow,
                )
            return agg_ops.accumulate(
                specs, lanes, gid, sel, cap,
                step="partial" if partial else "single",
                overflow_flags=self.sum_overflow,
                # decimal(38) sums ride the wide-mul retry ladder: the
                # narrow fast path flags a wrap, the retrace forces
                # true chunked 128-bit sums
                wide_flags=self.lowering.overflow_flags,
                force_wide=self.lowering.force_wide_mul,
                seg=seg,
            )

        def out_lanes(accs):
            if partial:
                return {
                    n: (v, jnp.ones(v.shape, bool)) for n, v in accs.items()
                }
            return agg_ops.finalize(specs, accs)

        if not node.keys:
            # global aggregation: one group
            gid = jnp.zeros(b.sel.shape[0], dtype=jnp.int64)
            accs = reduce_rows(b.lanes, gid, b.sel, 1)
            lanes = out_lanes(accs)
            for hs in host_specs:
                lanes[hs.output] = self._host_agg_lanes(
                    hs, b.lanes, gid, b.sel, 1
                )
            return self._finish_aggregate(
                node, [], lanes, jnp.ones(1, dtype=bool), 1
            )
        key_lanes = [b.lanes[k] for k in node.keys]
        domains = self._direct_domains(node.keys, types)
        if domains is not None:
            gid, cap = agg_ops.direct_group_ids(key_lanes, domains)
            accs = reduce_rows(b.lanes, gid, b.sel, cap)
            # _seg_count picks the masked/pallas form at small caps — a
            # raw segment_sum scatter here cost ~0.4s at SF1 (measured,
            # MICRO_group.json: scatter 0.58s vs masked 0.08s at 8.4M)
            present = agg_ops._seg_count(b.sel, gid, cap) > 0
            keys_out = agg_ops.group_keys_output(key_lanes, gid, b.sel, cap)
            host_src = (b.lanes, gid, b.sel)
        else:
            cap = min(self.ex.group_capacity, b.sel.shape[0])
            perm, gid, ngroups = self._group_sort(key_lanes, b.sel, cap)
            self._note_capacity(ngroups, cap)
            sel_sorted = b.sel[perm]
            from ..ops.filter_project import permute_lanes

            sorted_lanes = permute_lanes(b.lanes, perm)
            # gid is SORTED here: one shared run-range computation
            # replaces per-aggregate scatters (SortedSegments)
            ss = agg_ops.SortedSegments(gid, cap)
            accs = reduce_rows(sorted_lanes, gid, sel_sorted, cap, seg=ss)
            present = jnp.arange(cap) < ngroups
            keys_out = agg_ops.group_keys_output(
                [sorted_lanes[k] for k in node.keys], gid, sel_sorted, cap,
                starts=ss.starts,
            )
            host_src = (sorted_lanes, gid, sel_sorted)
        out = out_lanes(accs)
        for hs in host_specs:
            out[hs.output] = self._host_agg_lanes(hs, *host_src, cap)
        return self._finish_aggregate(node, keys_out, out, present, cap)

    def _merge_fused_sums(self, sums):
        """Fused-megakernel partial-sum merge seam: one device has
        nothing to merge; the mesh trace context overrides this with a
        cross-shard collective before the shared finalize tail."""
        return sums

    def _finish_aggregate(self, node, keys_out, out, present, cap):
        """Shared aggregate tail (unfused and megakernel paths): merge
        key and output lanes, pad to the static 128-aligned capacity."""
        lanes = {}
        for k, kl in zip(node.keys, keys_out):
            lanes[k] = kl
        for s in out:
            lanes[s] = out[s]
        pad_cap = self.ex.ladder.quantize(cap)
        if pad_cap != cap:
            from ..ops.wide_decimal import pad_rows

            lanes = {
                s: (pad_rows(v, pad_cap - cap), jnp.pad(ok, (0, pad_cap - cap)))
                for s, (v, ok) in lanes.items()
            }
            present = jnp.pad(present, (0, pad_cap - cap))
        return Batch(lanes, present)

    def _agg_dict_setup(self, node: P.Aggregate, b: "Batch"):
        """Dictionary handling for ordering/value-carrying aggregates.

        Dictionary codes are first-seen order, not string order, so min/max
        over a varchar (and the min_by/max_by ordering key) must compare
        lexicographic *ranks*: remap the code lane through the sorted
        dictionary and register the sorted dictionary for the output — the
        code-space analog of the reference ordering real strings through
        TypeOperators.  Value-carrying aggregates (arbitrary, min_by value)
        propagate the input dictionary unchanged.  Dictionaries are also
        registered for the $val/$key accumulator columns so PARTIAL-step
        output pages (shipped over exchanges) stay decodable."""
        raw_step = node.step in ("single", "partial")
        lanes = None
        aggs = []

        def rank_lane(sym: str):
            nonlocal lanes
            d = self.ex.dicts.get(sym)
            if d is None or len(d) == 0:
                return sym, d if d is not None else np.array([], dtype=object)
            order = np.argsort(np.array([str(x) for x in d]))
            rank = np.empty(len(d), dtype=np.int32)
            rank[order] = np.arange(len(d), dtype=np.int32)
            v, ok = b.lanes[sym]
            rk = jnp.asarray(rank)[jnp.clip(v, 0, len(d) - 1)]
            rsym = sym + "$rank"
            if lanes is None:
                lanes = dict(b.lanes)
            lanes[rsym] = (jnp.where(v >= 0, rk, -1).astype(v.dtype), ok)
            return rsym, d[order]

        for a in node.aggs:
            it, i2t = a.input_type, a.input2_type
            if (a.kind in ("min", "max") and it is not None
                    and it.is_dictionary):
                if raw_step:
                    rsym, sorted_d = rank_lane(a.arg)
                    a = dataclasses.replace(a, arg=rsym)
                    self.ex.dicts[a.output] = sorted_d
                    self.ex.dicts[f"{a.output}$val"] = sorted_d
                elif f"{a.output}$val" in self.ex.dicts:
                    self.ex.dicts[a.output] = self.ex.dicts[f"{a.output}$val"]
            elif a.kind in ("min_by", "max_by"):
                if i2t is not None and i2t.is_dictionary and raw_step:
                    rsym, sorted_d = rank_lane(a.arg2)
                    a = dataclasses.replace(a, arg2=rsym)
                    self.ex.dicts[f"{a.output}$key"] = sorted_d
                if it is not None and it.is_dictionary:
                    if raw_step and a.arg in self.ex.dicts:
                        self.ex.dicts[a.output] = self.ex.dicts[a.arg]
                        self.ex.dicts[f"{a.output}$val"] = self.ex.dicts[a.arg]
                    elif f"{a.output}$val" in self.ex.dicts:
                        self.ex.dicts[a.output] = (
                            self.ex.dicts[f"{a.output}$val"]
                        )
            elif a.output_type.is_dictionary:  # arbitrary etc.
                if raw_step and a.arg in self.ex.dicts:
                    self.ex.dicts[a.output] = self.ex.dicts[a.arg]
                    self.ex.dicts[f"{a.output}$val"] = self.ex.dicts[a.arg]
                elif f"{a.output}$val" in self.ex.dicts:
                    self.ex.dicts[a.output] = self.ex.dicts[f"{a.output}$val"]
            aggs.append(a)
        if lanes is not None:
            b = dataclasses.replace(b, lanes=lanes)
        return b, aggs

    def _direct_domains(self, keys, types) -> Optional[List[int]]:
        domains = []
        prod = 1
        for k in keys:
            t = types[k]
            if t.is_dictionary and k in self.ex.dicts:
                d = len(self.ex.dicts[k])
            elif t.name == "boolean":
                d = 2
            else:
                return None
            domains.append(d)
            prod *= d + 1
        return domains if prod <= 4096 else None

    # -- joins -----------------------------------------------------------
    def _note_capacity(self, ngroups, cap, kind="group"):
        # kind selects which knob the retry ladder grows on overflow:
        # group -> group_capacity, join -> join_factor (expansion /
        # shuffle buffers), topn -> topn_factor (candidate sets) —
        # uncoupled so a TopN tie burst cannot 8x every join buffer
        self.capacity_checks.append((ngroups, cap, kind))

    def _note_collision(self, coll):
        self.collision_checks.append(coll)

    def _group_sort(self, key_lanes, sel, cap):
        """Salted hash-sort grouping with exact verification; a
        detected locator collision re-runs the fragment under a fresh
        salt (executor retry ladder), so grouping is always exact."""
        perm, gid, ngroups, coll = agg_ops.sort_group_ids(
            key_lanes, sel, cap, getattr(self.ex, 'group_salt', 0)
        )
        self._note_collision(coll)
        return perm, gid, ngroups

    def _visit_join(self, node: P.Join) -> Batch:
        left = self.visit(node.left)
        right = self.visit(node.right)
        out = self._join_batches(node, left, right)
        if node.kind == "inner":
            out = self._maybe_compact(out, node)
        return out

    def _join_batches(self, node: P.Join, left: Batch, right: Batch) -> Batch:
        if node.kind == "cross":
            return self._cross_join(node, left, right)
        if node.expansion or id(node) in getattr(
            self.ex, "force_expansion", ()
        ):
            return self._expansion_join(node, left, right)
        # unique-keyed build on right, probe on left
        lkeys = [left.lanes[l] for l, _ in node.criteria]
        rkeys = [right.lanes[r] for _, r in node.criteria]
        self._check_join_dicts(node)
        # JOINT hashing decision: either side being multi-column or wide
        # forces both sides onto the hashed locator + exact verification
        need_verify = join_ops.needs_verification(
            rkeys
        ) or join_ops.needs_verification(lkeys)
        bkey = join_ops.composite_key(rkeys, right.sel, need_verify)
        pkey = join_ops.composite_key(lkeys, left.sel, need_verify)
        if (
            node.direct_domain is not None
            and not need_verify
            and id(node) not in getattr(self.ex, "force_no_direct", ())
        ):
            # dense-domain direct addressing: one scatter builds, one
            # gather probes; a violation/duplicate count retries on the
            # sorted unique kernel (then expansion if genuinely dup)
            lo, hi = node.direct_domain
            dsrc = join_ops.build_direct(
                bkey, right.sel, lo, hi - lo + 1
            )
            self.dup_checks.append((node, dsrc.violations))
            row, matched = join_ops.probe_direct(dsrc, pkey, left.sel)
        else:
            src = join_ops.build_unique(bkey, right.sel)
            self.dup_checks.append((node, src.dup_count))
            row, matched = join_ops.probe(src, pkey, left.sel)
        if need_verify:
            # exact equality on the real key columns: a 64-bit locator
            # collision must reject the candidate, not return a wrong row
            matched = matched & join_ops.verify_rows(rkeys, lkeys, row)
        build_cols = join_ops.gather_build(right.lanes, row, matched)
        lanes = dict(left.lanes)
        lanes.update(build_cols)
        if node.kind == "inner":
            sel = left.sel & matched
        elif node.kind == "left":
            sel = left.sel
        else:
            raise ExecutionError(f"join kind {node.kind} not supported yet")
        if node.filter is not None:
            f = compile_expr(node.filter, self.lowering)
            v, ok = f(lanes)
            if node.kind == "inner":
                sel = sel & v & ok
            else:
                # left join residual: failed residual nulls the build side
                keep = matched & v & ok
                for name in build_cols:
                    bv, bok = lanes[name]
                    lanes[name] = (bv, bok & keep)
        return Batch(lanes, sel)

    def _expansion_join(self, node: P.Join, left: Batch, right: Batch) -> Batch:
        """General (duplicate-build-key) join with static output capacity +
        host retry (vectorized LookupJoinOperator page building).

        Candidates come from the 64-bit locator ranges; `verify_rows` then
        enforces exact multi-column equality, and for outer joins the
        null-extended row is emitted per probe row only when *no* candidate
        survives key verification + residual filter (segment any-match),
        matching LookupJoinOperator.java:36 probe semantics exactly."""
        lkeys = [left.lanes[l] for l, _ in node.criteria]
        rkeys = [right.lanes[r] for _, r in node.criteria]
        self._check_join_dicts(node)
        need_verify = join_ops.needs_verification(
            rkeys
        ) or join_ops.needs_verification(lkeys)
        bkey = join_ops.composite_key(rkeys, right.sel, need_verify)
        pkey = join_ops.composite_key(lkeys, left.sel, need_verify)
        src = join_ops.build_multi(bkey, right.sel)
        counts, lo = join_ops.probe_counts(src, pkey, left.sel)
        if node.kind not in ("inner", "left"):
            raise ExecutionError(
                f"join kind {node.kind} not supported by the expansion "
                "kernel (right/full rewrite to left at planning)"
            )
        outer = node.kind == "left"
        probe_cap = left.sel.shape[0]
        capacity = self.ex.ladder.quantize(
            int(probe_cap * getattr(self.ex, "join_factor", 1))
        )
        probe_row, build_row, matched, total, k = join_ops.expand_join_slots(
            src, counts, lo, capacity, outer=outer
        )
        # the internal eff uses max(counts,1) for outer including unselected
        # rows; mask them below via probe sel gather
        self._note_capacity(total, capacity, "join")
        psel = left.sel[probe_row]
        if need_verify:
            matched = matched & join_ops.verify_rows(
                rkeys, lkeys, build_row, probe_row
            )
        from ..ops.filter_project import permute_lanes

        lanes = dict(permute_lanes(left.lanes, probe_row))
        for s, (v, ok) in right.lanes.items():
            lanes[s] = (v[build_row], ok[build_row] & matched)
        surviving = matched & psel  # matched is already within-capacity
        if node.filter is not None:
            f = compile_expr(node.filter, self.lowering)
            v, ok = f(lanes)
            surviving = surviving & v & ok
        if node.kind == "inner":
            sel = surviving
        else:
            any_match = (
                jax.ops.segment_sum(
                    surviving.astype(jnp.int32), probe_row,
                    num_segments=probe_cap,
                )
                > 0
            )
            within = jnp.arange(capacity) < total
            outer_emit = within & (k == 0) & psel & ~any_match[probe_row]
            sel = surviving | outer_emit
            for s in right.lanes:
                bv, bok = lanes[s]
                lanes[s] = (bv, bok & surviving)
        return Batch(lanes, sel)

    def _host_agg_lanes(self, spec, lanes, gid, sel, cap):
        """array_agg / map_agg / listagg: build per-group variable-length
        values HOST-side into a fresh dictionary (the engine's model for
        complex values — codes into a host dictionary, like
        expr/arrays.py).  Runs eagerly (the jit gate excludes plans with
        these aggregates), one python pass over the selected rows — the
        same single-threaded row walk the reference's accumulators do.
        Element values keep IR-constant conventions; Page.to_pylist
        decodes them (page._element_decoder)."""
        import numpy as np

        v, ok = lanes[spec.input]
        gid_np = np.asarray(gid)
        sel_np = np.asarray(sel)
        v_np = np.asarray(v)
        ok_np = np.asarray(ok)
        d_in = self.ex.dicts.get(spec.input)

        def v_of(i, arr, okarr, d):
            if not okarr[i]:
                return None
            x = arr[i].item()
            if d is not None:
                x = str(d[int(x)])
            return x

        groups: dict = {}
        if spec.kind == "map_agg":
            k2, ok2 = lanes[spec.input2]
            k_np, k_ok = np.asarray(k2), np.asarray(ok2)
            d_key = d_in
            d_val = self.ex.dicts.get(spec.input2)
            # spec.input is the KEY, input2 the VALUE (map_agg(key, value))
            for i in np.nonzero(sel_np)[0]:
                key = v_of(i, v_np, ok_np, d_key)
                if key is None:
                    continue  # NULL keys are skipped (reference behavior)
                g = groups.setdefault(int(gid_np[i]), {})
                g.setdefault(key, v_of(i, k_np, k_ok, d_val))
        else:
            for i in np.nonzero(sel_np)[0]:
                g = groups.setdefault(int(gid_np[i]), [])
                g.append(v_of(i, v_np, ok_np, d_in))

        entries: list = []
        index: dict = {}
        codes = np.full(cap, -1, dtype=np.int32)
        has = np.zeros(cap, dtype=bool)
        for gi, val in groups.items():
            if spec.kind == "array_agg":
                obj = tuple(val)
            elif spec.kind == "listagg":
                obj = str(spec.param).join(
                    str(x) for x in val if x is not None
                )
            else:  # map_agg: sorted key-value pair tuple
                obj = tuple(sorted(val.items(), key=lambda kv: repr(kv[0])))
            code = index.get(obj)
            if code is None:
                code = len(entries)
                index[obj] = code
                entries.append(obj)
            codes[gi] = code
            has[gi] = True
        # 1-D object array even when all entries are equal-length
        # tuples (np.array would build a 2-D array)
        d_out = np.empty(len(entries), dtype=object)
        d_out[:] = entries
        self.ex.dicts[spec.output] = d_out
        return (
            jnp.asarray(np.where(has, codes, 0)),
            jnp.asarray(has),
        )

    def _check_join_dicts(self, node: P.Join):
        for l, r in node.criteria:
            dl, dr = self.ex.dicts.get(l), self.ex.dicts.get(r)
            if (dl is None) != (dr is None):
                raise ExecutionError(
                    f"join key {l}={r} mixes varchar dictionary and non-dict"
                )
            if dl is not None and dl is not dr and not np.array_equal(dl, dr):
                raise ExecutionError(
                    f"join on varchar keys {l}={r} requires shared dictionary"
                )

    def _cross_join(self, node: P.Join, left: Batch, right: Batch) -> Batch:
        # a side whose PLAN guarantees at most one row (global aggregate,
        # LIMIT 1) broadcasts instead of repeat/tile — the scalar-ratio
        # query shape (TPC-DS Q90's amc/pmc) stays capacity-lean no
        # matter how wide the other side padded
        if _single_row_plan(node.right):
            return self._scalar_cross(left, right)
        if _single_row_plan(node.left):
            return self._scalar_cross(right, left)
        # only small-right cross joins (scalar-ish); replicate rows
        rcap = right.sel.shape[0]
        lcap = left.sel.shape[0]
        if rcap * lcap > 1 << 22:
            raise ExecutionError("cross join too large")
        # rows = left x right
        n = lcap * rcap
        li = jnp.repeat(jnp.arange(lcap), rcap)
        ri = jnp.tile(jnp.arange(rcap), lcap)
        lanes = {}
        for s, (v, ok) in left.lanes.items():
            lanes[s] = (v[li], ok[li])
        for s, (v, ok) in right.lanes.items():
            lanes[s] = (v[ri], ok[ri])
        sel = left.sel[li] & right.sel[ri]
        return Batch(lanes, sel)

    def _scalar_cross(self, keep: Batch, single: Batch) -> Batch:
        """Cross join against a ≤1-row side: broadcast its first selected
        row onto the kept side (empty single side = empty result, exactly
        the cross-join semantics)."""
        first = jnp.argmax(single.sel)
        has = single.sel.sum() > 0
        n = keep.sel.shape[0]
        lanes = dict(keep.lanes)
        for s, (v, ok) in single.lanes.items():
            lanes[s] = (
                jnp.broadcast_to(v[first], (n,) + v.shape[1:]),
                jnp.broadcast_to(ok[first] & has, (n,)),
            )
        return Batch(lanes, keep.sel & has)

    def _visit_semijoin(self, node: P.SemiJoin) -> Batch:
        src = self.visit(node.source)
        filt = self.visit(node.filtering)
        hit = self._semi_hit(node, src, filt)
        lanes = dict(src.lanes)
        lanes[node.output] = (hit, jnp.ones(hit.shape, bool))
        return Batch(lanes, src.sel, src.ordered, src.replicated)

    def _semi_hit(self, node: P.SemiJoin, src: Batch, filt: Batch):
        """Membership mark; duplicates in the filtering side are fine
        (sorted search, any match counts).  Single-column keys compare the
        real value directly (collision-free); multi-column keys and residual
        predicates go through the expansion path with exact verification."""
        skeys = [src.lanes[k] for k in node.source_keys]
        fkeys0 = [filt.lanes[k] for k in node.filtering_keys]
        if (
            node.filter is not None
            or join_ops.needs_verification(skeys)
            or join_ops.needs_verification(fkeys0)
        ):
            return self._semi_hit_expanded(node, src, filt)
        build = join_ops.build_multi(
            filt.lanes[node.filtering_keys[0]], filt.sel
        )
        counts, _ = join_ops.probe_counts(
            build, src.lanes[node.source_keys[0]], src.sel
        )
        return counts > 0

    def _semi_hit_expanded(self, node: P.SemiJoin, src: Batch, filt: Batch):
        """Mark join via candidate expansion: expand (source, filtering)
        pairs on the equi-key locator ranges, verify exact key equality,
        evaluate the residual if any, reduce any-match per source row
        (EXISTS with non-equality correlation, e.g. TPC-H Q21)."""
        fkeys = [filt.lanes[k] for k in node.filtering_keys]
        skeys = [src.lanes[k] for k in node.source_keys]
        need_verify = join_ops.needs_verification(
            fkeys
        ) or join_ops.needs_verification(skeys)
        bkey = join_ops.composite_key(fkeys, filt.sel, need_verify)
        pkey = join_ops.composite_key(skeys, src.sel, need_verify)
        build = join_ops.build_multi(bkey, filt.sel)
        counts, lo = join_ops.probe_counts(build, pkey, src.sel)
        n_src = src.sel.shape[0]
        capacity = self.ex.ladder.quantize(
            int(n_src * getattr(self.ex, "join_factor", 1))
        )
        probe_row, build_row, matched, total, _ = join_ops.expand_join_slots(
            build, counts, lo, capacity
        )
        self._note_capacity(total, capacity, "join")
        if need_verify:
            matched = matched & join_ops.verify_rows(
                fkeys, skeys, build_row, probe_row
            )
        pair_ok = matched & src.sel[probe_row]
        if node.filter is not None:
            lanes = {}
            for s, (v, ok) in src.lanes.items():
                lanes[s] = (v[probe_row], ok[probe_row])
            for s, (v, ok) in filt.lanes.items():
                lanes[s] = (v[build_row], ok[build_row] & matched)
            f = compile_expr(node.filter, self.lowering)
            fv, fok = f(lanes)
            pair_ok = pair_ok & fv & fok
        marks = jax.ops.segment_sum(
            pair_ok.astype(jnp.int32), probe_row, num_segments=n_src
        )
        return marks > 0

    def _visit_scalarjoin(self, node: P.ScalarJoin) -> Batch:
        src = self.visit(node.source)
        sub = self.visit(node.subquery)
        # single row: first selected row of sub (EnforceSingleRow)
        first = jnp.argmax(sub.sel)
        n = src.sel.shape[0]
        lanes = dict(src.lanes)
        for s, (v, ok) in sub.lanes.items():
            val = v[first]
            okv = ok[first] & (sub.sel.sum() > 0)
            shape = (n,) + val.shape  # wide decimals keep their limb dim
            lanes[s] = (
                jnp.broadcast_to(val, shape),
                jnp.broadcast_to(okv, (n,)),
            )
        return Batch(lanes, src.sel, src.ordered, src.replicated)

    # -- ordering --------------------------------------------------------
    def _visit_sort(self, node: P.Sort) -> Batch:
        b = self.visit(node.source)
        keys = self._rank_sort_keys(node.keys, b)
        perm = sort_ops.sort_perm(keys, b.lanes, b.sel)
        lanes, sel = sort_ops.apply_perm(b.lanes, perm, b.sel)
        return Batch(lanes, sel, ordered=True, replicated=b.replicated)

    def _visit_topn(self, node: P.TopN) -> Batch:
        b = self.visit(node.source)
        keys = self._rank_sort_keys(node.keys, b)
        lanes, sel, check = sort_ops.topn(
            keys, b.lanes, b.sel, node.count,
            getattr(self.ex, 'topn_factor', 1),
        )
        if check is not None:
            self._note_capacity(check[0], check[1], "topn")
        return Batch(lanes, sel, ordered=True, replicated=b.replicated)

    def _rank_sort_keys(self, keys, b: Batch):
        """Replace dict-coded sort columns by their lexicographic ranks."""
        out = []
        for k in keys:
            d = self.ex.dicts.get(k.column)
            if d is not None and len(d) == 0:
                d = None  # zero-row split: codes are all sentinels
            if d is not None:
                # DENSE ranks: generated dictionaries can carry duplicate
                # strings under distinct codes, and ordinal ranks would
                # order equal values by dictionary layout — hiding the
                # next sort key and making the order differ between the
                # monolithic and tiled (merged-dictionary) paths
                dd = np.asarray(d, dtype=str)
                order = np.argsort(dd, kind="stable")
                sd = dd[order]
                dense = np.zeros(len(d), dtype=np.int64)
                if len(d) > 1:
                    dense[1:] = np.cumsum(sd[1:] != sd[:-1])
                ranks = np.empty(len(d), dtype=np.int64)
                ranks[order] = dense
                v, ok = b.lanes[k.column]
                rank_tbl = jnp.asarray(ranks)
                safe = jnp.clip(v, 0, len(d) - 1)
                rv = jnp.where(v >= 0, rank_tbl[safe], -1)
                hidden = f"{k.column}$rank"
                b.lanes[hidden] = (rv, ok)
                out.append(
                    sort_ops.SortKey(hidden, k.ascending, k.nulls_first)
                )
            else:
                out.append(k)
        return out

    # -- window functions ------------------------------------------------
    def _visit_window(self, node: P.Window) -> Batch:
        """WindowOperator: one sort groups partitions and orders peers,
        then every function is a vector program over the sorted arrays
        (ops/window.py)."""
        b = self.visit(node.source)
        part_keys = tuple(
            sort_ops.SortKey(s) for s in node.partition_by
        )
        order_keys = tuple(self._rank_sort_keys(node.order_by, b))
        perm = sort_ops.sort_perm(part_keys + order_keys, b.lanes, b.sel)
        lanes, sel = sort_ops.apply_perm(b.lanes, perm, b.sel)
        part_lanes = [lanes[s] for s in node.partition_by]
        ord_lanes = [lanes[k.column] for k in order_keys]
        bounds = window_ops.compute_bounds(part_lanes, ord_lanes, sel)
        for f in node.functions:
            lanes[f.output] = self._window_output(f, lanes, sel, bounds)
            if f.args:
                d = self.ex.dicts.get(f.args[0])
                if d is not None and f.output_type.is_dictionary:
                    self.ex.dicts[f.output] = d
        return Batch(lanes, sel, ordered=False, replicated=b.replicated)

    def _window_output(self, f: P.WindowFunc, lanes, sel, b):
        W = window_ops
        if f.kind == "row_number":
            return W.row_number(b)
        if f.kind == "rank":
            return W.rank(b)
        if f.kind == "dense_rank":
            return W.dense_rank(b)
        if f.kind == "percent_rank":
            return W.percent_rank(b, sel)
        if f.kind == "cume_dist":
            return W.cume_dist(b, sel)
        if f.kind == "ntile":
            return W.ntile(b, sel, f.constants[0])
        if f.kind in ("lag", "lead"):
            off, default = f.constants
            return W.shift_value(
                lanes[f.args[0]], b, off, default, f.kind == "lead"
            )
        start, end = W.frame_range(f.frame, b)
        nonempty = end >= start
        if f.kind == "first_value":
            return W.value_at(lanes[f.args[0]], start, nonempty)
        if f.kind == "last_value":
            return W.value_at(lanes[f.args[0]], end, nonempty)
        if f.kind == "nth_value":
            return W.nth_value(lanes[f.args[0]], start, end, f.constants[0])
        if f.kind in ("count", "count_star"):
            lane = lanes[f.args[0]] if f.args else None
            _, cnt = W.framed_sum_count(
                lane, sel, start, end, count_star=f.kind == "count_star"
            )
            return cnt, jnp.ones(cnt.shape, bool)
        if f.kind in ("min", "max"):
            if lanes[f.args[0]][0].ndim == 2:
                # wide (two-limb) decimal lane: limb-wise masked compares
                v, cnt = W.framed_minmax_wide(
                    lanes[f.args[0]], sel, b, f.frame, f.kind
                )
                return (
                    jnp.where((cnt > 0)[:, None], v, jnp.zeros_like(v)),
                    cnt > 0,
                )
            v, cnt = W.framed_minmax(lanes[f.args[0]], sel, b, f.frame, f.kind)
            return jnp.where(cnt > 0, v, jnp.zeros_like(v)), cnt > 0
        if f.kind in ("sum", "avg"):
            ot, it_ = f.output_type, f.input_type
            in_lane = lanes[f.args[0]]
            wide_out = getattr(ot, "wide", False)
            if wide_out or in_lane[0].ndim == 2:
                # exact 128-bit windowed decimal sum (chunk cumsums)
                from ..ops import wide_decimal as wd

                wsum, cnt = W.framed_sum_wide(in_lane, sel, start, end)
                if f.kind == "sum":
                    return (
                        (wsum if wide_out else wd.narrow(wsum)), cnt > 0
                    )
                num = wd.rescale(wsum, ot.scale - it_.scale)
                q = wd.div_round(num, jnp.maximum(cnt, 1))
                return (q if wide_out else wd.narrow(q)), cnt > 0
            ssum, cnt = W.framed_sum_count(in_lane, sel, start, end)
            if f.kind == "sum":
                return ssum, cnt > 0
            den = jnp.maximum(cnt, 1)
            if ssum.dtype.kind == "f":
                v = ssum / den
            elif ot.name in ("double", "real"):
                v = ssum.astype(ot.np_dtype) / den
            elif ot.is_decimal and it_ is not None:
                shift = 10 ** (ot.scale - it_.scale)
                num = ssum * shift
                sign = jnp.sign(num)
                anum = jnp.abs(num)
                q = anum // den
                rem = anum - q * den
                v = sign * (q + (2 * rem >= den))
            else:
                v = ssum // den
            return v, cnt > 0
        raise ExecutionError(f"window function {f.kind} not implemented")

    # -- set ops ---------------------------------------------------------
    def _visit_setoperation(self, node: P.SetOperation) -> Batch:
        """UNION [ALL] / INTERSECT / EXCEPT (UnionNode, IntersectNode,
        ExceptNode).  Intersect/except use distinct semantics via one sort
        over the concatenated inputs with per-side presence counts (the
        reference lowers them to union + mark + filter; here the sort-based
        group machinery does both in one kernel)."""
        if node.kind in ("intersect", "except"):
            return self._intersect_except(node)
        lanes, sel, _ = self._union_lanes(node)
        batch = Batch(lanes, sel)
        if not node.all:
            # UNION DISTINCT via the Distinct path
            key_lanes = [lanes[s] for s in node.symbols]
            cap = sel.shape[0]
            perm, gid, _ = self._group_sort(key_lanes, sel, cap)
            boundary = jnp.concatenate(
                [jnp.ones(1, dtype=bool), gid[1:] != gid[:-1]]
            )
            lanes = {s: (v[perm], ok[perm]) for s, (v, ok) in lanes.items()}
            batch = Batch(lanes, sel[perm] & boundary)
        return batch

    def _union_lanes(self, node: P.SetOperation):
        """Visit and concatenate all inputs positionally; returns
        (lanes, sel, per-input capacities)."""
        batches = [self.visit(i) for i in node.inputs]
        caps = [b.sel.shape[0] for b in batches]
        lanes = {}
        for pos, (out_sym, (_, t)) in enumerate(zip(node.symbols, node.types_)):
            vs, oks = [], []
            src_syms = [inp.output_symbols()[pos] for inp in node.inputs]
            if t.is_dictionary:
                # re-encode each input's codes into a merged dictionary
                in_dicts = [self.ex.dicts.get(s) for s in src_syms]
                if any(d is None for d in in_dicts):
                    raise ExecutionError("union of non-dict varchar")
                merged: List[str] = []
                index: Dict[str, int] = {}
                remaps = []
                for d in in_dicts:
                    table = np.empty(len(d), dtype=np.int32)
                    for i, s in enumerate(d):
                        if s not in index:
                            index[s] = len(merged)
                            merged.append(s)
                        table[i] = index[s]
                    remaps.append(jnp.asarray(table))
                self.ex.dicts[out_sym] = np.array(merged, dtype=object)
                from ..expr.functions import dict_gather

                for b, s, tbl in zip(batches, src_syms, remaps):
                    v, ok = b.lanes[s]
                    vs.append(dict_gather(tbl, v, -1).astype(jnp.int32))
                    oks.append(ok)
            else:
                wide_t = getattr(t, "wide", False)
                for b, s in zip(batches, src_syms):
                    v, ok = b.lanes[s]
                    if wide_t:
                        # inputs may mix two-limb lanes with narrow
                        # fast-path lanes of the same wide type
                        from ..ops.wide_decimal import promote

                        vs.append(promote(v.astype(jnp.int64) if v.ndim == 1 else v))
                    else:
                        vs.append(v.astype(t.np_dtype))
                    oks.append(ok)
            lanes[out_sym] = (jnp.concatenate(vs), jnp.concatenate(oks))
        sel = jnp.concatenate([b.sel for b in batches])
        return lanes, sel, caps

    def _setop_tag_reduce(self, node, lanes0, sel, tag, cap):
        """Shared INTERSECT/EXCEPT membership reduction over tagged
        rows: group-sort by the full row, per-side presence marks,
        keep-group predicate, first-of-group dedup.  Used by the local
        path and (post-repartition) by the mesh path."""
        key_lanes = [lanes0[s] for s in node.symbols]
        perm, gid, ngroups = self._group_sort(key_lanes, sel, cap)
        self._note_capacity(ngroups, cap)
        sel_sorted = sel[perm]
        tag_sorted = tag[perm]
        side0 = agg_ops._seg_count(
            sel_sorted & (tag_sorted == 0), gid, cap
        ) > 0
        side1 = agg_ops._seg_count(
            sel_sorted & (tag_sorted == 1), gid, cap
        ) > 0
        keep_group = (
            side0 & side1 if node.kind == "intersect" else side0 & ~side1
        )
        boundary = jnp.concatenate(
            [jnp.ones(1, dtype=bool), gid[1:] != gid[:-1]]
        )
        from ..ops.filter_project import permute_lanes

        lanes = permute_lanes(lanes0, perm)
        return Batch(lanes, sel_sorted & boundary & keep_group[gid])

    def _intersect_except(self, node: P.SetOperation) -> Batch:
        if node.all:
            raise ExecutionError(
                f"{node.kind.upper()} ALL not supported (DISTINCT only)"
            )
        assert len(node.inputs) == 2
        lanes0, sel, caps = self._union_lanes(node)
        tag = jnp.concatenate([
            jnp.zeros(caps[0], dtype=jnp.int32),
            jnp.ones(caps[1], dtype=jnp.int32),
        ])
        return self._setop_tag_reduce(node, lanes0, sel, tag, sel.shape[0])


LocalExecutor.trace_ctx_cls = _TraceCtx
