"""Statement client: the nextUri pull loop.

Reference parity: client/trino-client StatementClientV1.java:69 —
POST /v1/statement (:141), advance() loop (:349) following nextUri until
FINISHED/FAILED, accumulating data pages.

Coordinator-restart transparency (server/recovery.py): nextUri tokens
encode the query id (never an in-memory handle), so the poll loop rides
out a coordinator kill -9 + restart:

  - connection refused / reset while the process is down: bounded
    backoff up to ``restart_grace_s`` (the same-port restart re-binds
    within that window) instead of three fast attempts and death;
  - HTTP 503 + Retry-After during the recovery window (the restarted
    coordinator is still replaying its WAL): wait as told and re-poll;
  - a structured retryable error document (errorName
    COORDINATOR_RESTART, retriable=true — the orphaned-pipelined-query
    verdict): re-submit the original SQL once per allowance, exactly the
    reference client's retry class for EXTERNAL failures.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

# transient transport blips (a loaded ThreadingHTTPServer resets the odd
# connection) get this many fast retries before the restart grace kicks in
FAST_POLL_ATTEMPTS = 3


class ClientError(RuntimeError):
    pass


class StatementClient:
    def __init__(self, server: str, user: str = "trino-tpu",
                 password: Optional[str] = None, source: str = "",
                 restart_grace_s: float = 10.0,
                 max_resubmits: int = 1):
        self.server = server.rstrip("/")
        self.user = user
        self.password = password
        self.source = source
        # how long polls survive a dead/restarting coordinator before
        # the failure is surfaced (0 restores fail-fast behavior)
        self.restart_grace_s = float(restart_grace_s)
        # how many times a structured retryable error (COORDINATOR_
        # RESTART) re-submits the original SQL before surfacing
        self.max_resubmits = int(max_resubmits)

    def _headers(self) -> dict:
        headers = {"X-Trino-User": self.user}
        if self.source:
            headers["X-Trino-Source"] = self.source
        if self.password is not None:
            import base64

            cred = base64.b64encode(
                f"{self.user}:{self.password}".encode()
            ).decode()
            headers["Authorization"] = f"Basic {cred}"
        return headers

    def execute(self, sql: str) -> Tuple[List[dict], List[list]]:
        """Returns (columns, rows)."""
        resubmits = 0
        while True:
            try:
                return self._execute_once(sql)
            except ClientError as e:
                if (
                    getattr(e, "retryable", False)
                    and resubmits < self.max_resubmits
                ):
                    # the server said this failure is the SERVER'S fault
                    # and safe to retry (coordinator restart orphaned a
                    # pipelined query): re-submit, don't surface
                    resubmits += 1
                    continue
                raise

    def _execute_once(self, sql: str) -> Tuple[List[dict], List[list]]:
        headers = self._headers()
        req = urllib.request.Request(
            f"{self.server}/v1/statement",
            data=sql.encode(),
            method="POST",
            headers=headers,
        )
        with urllib.request.urlopen(req) as resp:
            doc = json.load(resp)
        columns: List[dict] = []
        rows: List[list] = []
        while True:
            if "columns" in doc:
                columns = doc["columns"]
            if "data" in doc:
                rows.extend(doc["data"])
            err = doc.get("error")
            if err:
                e = ClientError(err.get("message", "query failed"))
                e.retryable = bool(err.get("retriable"))
                e.error_name = err.get("errorName")
                raise e
            nxt = doc.get("nextUri")
            if not nxt:
                break
            doc = self._poll(nxt, headers)
        return columns, rows

    def _poll(self, nxt: str, headers: dict) -> dict:
        """One idempotent status GET, retried through transport blips,
        coordinator downtime (restart grace), and 503 recovery waits."""
        grace_deadline = time.time() + self.restart_grace_s
        attempt = 0
        while True:
            poll = urllib.request.Request(
                self.server + nxt, headers=headers
            )
            try:
                with urllib.request.urlopen(poll) as resp:
                    return json.load(resp)
            except urllib.error.HTTPError as e:
                if e.code == 503 and time.time() < grace_deadline:
                    # recovery window: the restarted coordinator is
                    # still replaying its WAL — wait as told, re-poll
                    try:
                        retry_after = float(
                            e.headers.get("Retry-After") or 1.0
                        )
                    except (TypeError, ValueError):
                        retry_after = 1.0
                    time.sleep(min(retry_after, 2.0))
                    continue
                raise
            except (ConnectionResetError, urllib.error.URLError):
                attempt += 1
                if attempt < FAST_POLL_ATTEMPTS:
                    time.sleep(0.05 * attempt)
                    continue
                if time.time() >= grace_deadline:
                    raise
                # the coordinator itself is down (refused/reset beyond
                # transient): a same-port restart re-binds within the
                # grace window, and the query-id-addressed nextUri stays
                # valid across it
                time.sleep(0.25)
