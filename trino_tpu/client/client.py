"""Statement client: the nextUri pull loop.

Reference parity: client/trino-client StatementClientV1.java:69 —
POST /v1/statement (:141), advance() loop (:349) following nextUri until
FINISHED/FAILED, accumulating data pages.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple


class ClientError(RuntimeError):
    pass


class StatementClient:
    def __init__(self, server: str, user: str = "trino-tpu",
                 password: Optional[str] = None, source: str = ""):
        self.server = server.rstrip("/")
        self.user = user
        self.password = password
        self.source = source

    def execute(self, sql: str) -> Tuple[List[dict], List[list]]:
        """Returns (columns, rows)."""
        headers = {"X-Trino-User": self.user}
        if self.source:
            headers["X-Trino-Source"] = self.source
        if self.password is not None:
            import base64

            cred = base64.b64encode(
                f"{self.user}:{self.password}".encode()
            ).decode()
            headers["Authorization"] = f"Basic {cred}"
        req = urllib.request.Request(
            f"{self.server}/v1/statement",
            data=sql.encode(),
            method="POST",
            headers=headers,
        )
        with urllib.request.urlopen(req) as resp:
            doc = json.load(resp)
        columns: List[dict] = []
        rows: List[list] = []
        while True:
            if "columns" in doc:
                columns = doc["columns"]
            if "data" in doc:
                rows.extend(doc["data"])
            err = doc.get("error")
            if err:
                raise ClientError(err.get("message", "query failed"))
            nxt = doc.get("nextUri")
            if not nxt:
                break
            # status polls are idempotent GETs: retry transient
            # transport failures (a loaded ThreadingHTTPServer resets
            # the odd connection) instead of failing the whole query
            for attempt in range(3):
                poll = urllib.request.Request(
                    self.server + nxt, headers=headers
                )
                try:
                    with urllib.request.urlopen(poll) as resp:
                        doc = json.load(resp)
                    break
                except (ConnectionResetError, urllib.error.URLError):
                    if attempt == 2:
                        raise
                    time.sleep(0.05 * (attempt + 1))
        return columns, rows
