"""DB-API 2.0 (PEP 249) driver — the JDBC-driver analog.

Reference parity: client/trino-jdbc (TrinoDriver/TrinoConnection/
TrinoResultSet built over the statement protocol).  Python programs use
this the way Java programs use the JDBC driver:

    import trino_tpu.client.dbapi as dbapi
    conn = dbapi.connect("http://127.0.0.1:8080", user="alice")
    cur = conn.cursor()
    cur.execute("select * from nation where n_regionkey = ?", (3,))
    rows = cur.fetchall()

Parameters use qmark style and are bound client-side with literal
substitution (strings escaped), like the reference's simple prepared-
statement emulation before server-side EXECUTE.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


def _quote(value) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (list, tuple)):
        return "ARRAY[" + ", ".join(_quote(v) for v in value) + "]"
    return "'" + str(value).replace("'", "''") + "'"


def _bind(sql: str, params: Sequence) -> str:
    """qmark substitution outside string literals."""
    out = []
    it = iter(params)
    in_str = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if ch == "'":
            in_str = not in_str
            out.append(ch)
        elif ch == "?" and not in_str:
            try:
                out.append(_quote(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters") from None
        else:
            out.append(ch)
        i += 1
    leftover = sum(1 for _ in it)
    if leftover:
        raise ProgrammingError(f"{leftover} unused parameter(s)")
    return "".join(out)


class Cursor:
    arraysize = 1

    def __init__(self, connection: "Connection"):
        self.connection = connection
        self.description: Optional[List[tuple]] = None
        self.rowcount = -1
        self._rows: List[tuple] = []
        self._pos = 0
        self._closed = False

    # -- execution ------------------------------------------------------
    def execute(self, operation: str, parameters: Sequence = ()) -> "Cursor":
        if self._closed:
            raise InterfaceError("cursor is closed")
        sql = _bind(operation, parameters or ())
        try:
            cols, rows = self.connection._run(sql)
        except Error:
            raise
        except Exception as e:
            raise DatabaseError(str(e)) from e
        self.description = [
            (c["name"], c.get("type", "unknown"), None, None, None, None,
             None)
            for c in cols
        ]
        self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def executemany(self, operation: str, seq_of_parameters) -> "Cursor":
        for p in seq_of_parameters:
            self.execute(operation, p)
        return self

    # -- fetching -------------------------------------------------------
    def fetchone(self) -> Optional[tuple]:
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[tuple]:
        n = size if size is not None else self.arraysize
        out = self._rows[self._pos : self._pos + n]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[tuple]:
        out = self._rows[self._pos :]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self):
        self._closed = True

    # no-ops required by PEP 249
    def setinputsizes(self, sizes):
        pass

    def setoutputsize(self, size, column=None):
        pass


class Connection:
    def __init__(self, target, user: str = "dbapi",
                 password: Optional[str] = None, source: str = ""):
        self._closed = False
        self._session = None
        self._client = None
        if isinstance(target, str):
            from .client import StatementClient

            self._client = StatementClient(
                target, user=user, password=password, source=source
            )
        else:  # in-process Session (the PlanTester-style embedded mode)
            self._session = target
            self._user = user

    def _run(self, sql: str) -> Tuple[List[dict], List[list]]:
        if self._closed:
            raise InterfaceError("connection is closed")
        if self._client is not None:
            return self._client.execute(sql)
        page = self._session.execute(sql, user=self._user)
        types = [c.type for c in page.columns]
        cols = [
            {"name": n, "type": str(t)}
            for n, t in zip(page.names, types)
        ]
        return cols, [list(r) for r in page.to_pylist()]

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def commit(self):
        pass  # autocommit (per-statement transactions)

    def rollback(self):
        raise DatabaseError("rollback is not supported (autocommit)")

    def close(self):
        self._closed = True


def connect(target, user: str = "dbapi", password: Optional[str] = None,
            source: str = "") -> Connection:
    """target: server URI ('http://host:port') or an in-process Session."""
    return Connection(target, user, password, source)
